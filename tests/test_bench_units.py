"""Cheap regression cover for bench.py helpers (the slow arms run under
the driver; these keep the harness itself from rotting)."""

import json
import subprocess
import sys

sys.path.insert(0, "/root/repo")

import bench


class TestWorkload:
    def test_deterministic(self):
        import numpy as np

        a = bench.build_workload(np.random.default_rng(42), n_requests=8)
        b = bench.build_workload(np.random.default_rng(42), n_requests=8)
        assert a == b

    def test_shared_prefixes(self):
        import numpy as np

        wl = bench.build_workload(np.random.default_rng(0), n_requests=32,
                                  n_prefixes=4, prefix_len=16, suffix_len=4)
        prefixes = {tuple(p[:16]) for p in wl}
        assert len(prefixes) <= 4  # requests reuse the prefix pool
        assert all(len(p) == 20 for p in wl)


class TestQueueingTTFTs:
    def test_no_arrivals_returns_bare_service(self):
        assert bench.queueing_ttfts([1.0, 2.0], ["a", "b"], None) == [1.0, 2.0]

    def test_fifo_queue_wait_accumulates_per_pod(self):
        # Both requests hit pod "a"; the second arrives at t=0 but waits
        # for the first's service to finish.
        ttfts = bench.queueing_ttfts([1.0, 1.0], ["a", "a"], [0.0, 0.0])
        assert ttfts == [1.0, 2.0]

    def test_independent_pods_do_not_queue(self):
        ttfts = bench.queueing_ttfts([1.0, 1.0], ["a", "b"], [0.0, 0.0])
        assert ttfts == [1.0, 1.0]

    def test_idle_gap_resets_queue(self):
        # Second arrival lands after the first completes: no wait.
        ttfts = bench.queueing_ttfts([1.0, 1.0], ["a", "a"], [0.0, 5.0])
        assert ttfts == [1.0, 1.0]


class TestRunConcurrent:
    """The concurrent arm against real tiny engines: every request gets a
    TTFT, queueing shows up, and decode load is served to completion."""

    @staticmethod
    def _fleet(n_pods=2, num_pages=64):
        from llmd_kv_cache_tpu.core import TokenProcessorConfig
        from llmd_kv_cache_tpu.models import engine as engine_mod
        from llmd_kv_cache_tpu.models.llama import LlamaConfig
        from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig

        cfg = LlamaConfig.tiny()
        indexer = Indexer(IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size_tokens=cfg.page_size)))
        pods = bench.make_pods(
            n_pods, cfg, engine_mod, indexer,
            pod_kw={"num_pages": num_pages, "max_pages_per_seq": 16})
        return pods, indexer

    def test_all_requests_served_with_queueing(self):
        import numpy as np

        pods, _ = self._fleet()
        wl = bench.build_workload(np.random.default_rng(3), n_requests=8,
                                  n_prefixes=2, prefix_len=12, suffix_len=4,
                                  vocab=200)
        # Two bursts: 4 requests at t=0 (they must queue behind each
        # other's service) and 4 long after (no queueing).
        arrivals = [0.0, 0.0, 0.0, 0.0, 1e6, 1e6 + 1, 1e6 + 2, 1e6 + 3]
        ttfts, hit, out_tps, decode = bench.run_concurrent(
            pods, wl, bench.make_rr_router(), arrivals,
            max_new_tokens=4)
        assert len(ttfts) == 8 and all(t > 0 for t in ttfts)
        assert 0.0 <= hit <= 1.0
        # 8 requests x 4 decoded tokens over a positive makespan.
        assert out_tps > 0
        # Decode latency accounting: 3 inter-token gaps per request (4
        # tokens), one TPOT per request, all positive virtual times.
        assert len(decode["itl"]) == 8 * 3
        assert len(decode["tpot"]) == 8
        assert all(g > 0 for g in decode["itl"])
        assert all(t > 0 for t in decode["tpot"])
        # Every request decoded to completion through step().
        for p in pods.values():
            assert not p._running
        # The t=0 burst on each pod queues: later requests of the burst
        # wait for earlier ones, so the burst's worst TTFT strictly
        # exceeds its best (same pods serve one prefill at a time).
        burst = sorted(ttfts[:4])
        assert burst[-1] > burst[0]

    def test_page_pressure_defers_admission(self):
        import numpy as np

        # A pool sized for ~1.5 in-flight requests: the second concurrent
        # admission must retry until the first finishes, not crash.
        pods, _ = self._fleet(n_pods=1, num_pages=24)
        wl = bench.build_workload(np.random.default_rng(4), n_requests=4,
                                  n_prefixes=1, prefix_len=12, suffix_len=4,
                                  vocab=200)
        arrivals = [0.0, 0.0, 0.0, 0.0]
        ttfts, _, _, _ = bench.run_concurrent(
            pods, wl, lambda *_a, **_kw: "pod-0", arrivals,
            max_new_tokens=4)
        assert len(ttfts) == 4 and all(t > 0 for t in ttfts)


class TestBenchModes:
    def test_index_bench_emits_valid_json(self):
        result = bench.bench_index_add()
        assert result["unit"] == "ns/op"
        assert result["value"] > 0
        assert result["vs_baseline"] > 0
        json.dumps(result)

    def test_python_fallback_mode(self):
        result = bench.bench_index_add(native=False)
        assert "python" in result["metric"]

    def test_cli_index_mode(self):
        out = subprocess.run(
            [sys.executable, "bench.py", "--index"],
            capture_output=True, text=True, timeout=300, cwd="/root/repo",
            env={"PATH": "/usr/bin:/bin:/opt/venv/bin"},
        )
        line = out.stdout.strip().splitlines()[-1]
        parsed = json.loads(line)
        assert set(parsed) == {"metric", "value", "unit", "vs_baseline"}


class TestGuardedLadder:
    """The driver entry's fallback ladder: probe -> device TTFT -> CPU-env
    TTFT -> index micro-bench."""

    def test_cpu_rung_strips_accelerator_env(self, monkeypatch):
        import bench

        calls = []

        def fake_ttft(env=None, timeout=900):
            calls.append(env)
            if env is None:
                return None  # device rung fails
            return '{"metric": "m", "value": 1, "unit": "%", "vs_baseline": 1}'

        monkeypatch.setattr(bench, "_accelerator_healthy", lambda: True)
        monkeypatch.setattr(bench, "_run_ttft_subprocess", fake_ttft)
        monkeypatch.setenv("PYTHONPATH", "/some/plugin")
        line = bench.guarded_main()
        assert line.startswith('{"metric"')
        assert calls[0] is None  # device rung ran first
        cpu_env = calls[1]
        assert "PYTHONPATH" not in cpu_env
        assert cpu_env["JAX_PLATFORMS"] == "cpu"

    def test_unhealthy_probe_skips_device_rung(self, monkeypatch):
        import bench

        calls = []

        def fake_ttft(env=None, timeout=900):
            calls.append(env)
            return '{"metric": "m", "value": 1, "unit": "%", "vs_baseline": 1}'

        monkeypatch.setattr(bench, "_accelerator_healthy", lambda: False)
        monkeypatch.setattr(bench, "_run_ttft_subprocess", fake_ttft)
        bench.guarded_main()
        assert len(calls) == 1 and calls[0] is not None  # straight to CPU

    def test_all_ttft_rungs_failing_falls_to_index_bench(self, monkeypatch):
        import json

        import bench

        monkeypatch.setattr(bench, "_accelerator_healthy", lambda: False)
        monkeypatch.setattr(bench, "_run_ttft_subprocess",
                            lambda env=None, timeout=900: None)
        out = json.loads(bench.guarded_main())
        assert "value" in out and "vs_baseline" in out


class TestPerfSentinel:
    """The perf-regression gate's verdict-line grammar and exit codes
    (``hack/perf_sentinel.py``, wired into ``make perf-check``)."""

    @staticmethod
    def _sentinel():
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "perf_sentinel", "/root/repo/hack/perf_sentinel.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    BASELINE = {
        "benches": {
            "pyprof-overhead": {"baseline": 0.5,
                                "max_regression_pct": 100.0,
                                "direction": "lower_is_better"},
        },
        "hot_functions": {
            "llm_d.kv_cache.score_tokens": {"tracing.py:export": 0.25},
        },
    }

    def _result(self, value, export_share=0.01):
        return {"metric": "pyprof_overhead_pct", "value": value,
                "unit": "%", "vs_baseline": 1.0,
                "hot_functions": {"llm_d.kv_cache.score_tokens": {
                    "samples": 100,
                    "functions": {"native.py:score": 1.0 - export_share,
                                  "tracing.py:export": export_share}}}}

    def test_healthy_run_passes_every_check(self):
        sentinel = self._sentinel()
        lines, failed = sentinel.evaluate(
            self.BASELINE, {"pyprof-overhead": self._result(0.6)})
        assert failed == 0
        assert lines[0] == ("PERF PASS bench:pyprof-overhead "
                            "value=0.6 baseline=0.5 limit=1")
        assert lines[1] == ("PERF PASS hotfn:llm_d.kv_cache.score_tokens:"
                            "tracing.py:export share=0.01 max=0.25")
        assert lines[-1] == "PERF OVERALL PASS checks=2 failed=0"

    def test_bench_regression_fails_with_verdict_line(self):
        sentinel = self._sentinel()
        lines, failed = sentinel.evaluate(
            self.BASELINE, {"pyprof-overhead": self._result(1.31)})
        assert failed == 1
        assert lines[0].startswith(
            "PERF FAIL bench:pyprof-overhead value=1.31")
        assert "(regression +162.0%)" in lines[0]
        assert lines[-1] == "PERF OVERALL FAIL checks=2 failed=1"

    def test_injected_hot_function_regression_fails(self):
        # The headline latency gate still passes, but a capped function
        # claims 40% of the span's samples: the sentinel must FAIL.
        sentinel = self._sentinel()
        lines, failed = sentinel.evaluate(
            self.BASELINE,
            {"pyprof-overhead": self._result(0.6, export_share=0.4)})
        assert failed == 1
        assert ("PERF FAIL hotfn:llm_d.kv_cache.score_tokens:"
                "tracing.py:export share=0.4 max=0.25") in lines
        assert lines[-1] == "PERF OVERALL FAIL checks=2 failed=1"

    def test_missing_gated_bench_fails_loudly(self):
        sentinel = self._sentinel()
        lines, failed = sentinel.evaluate(self.BASELINE, {})
        assert failed == 1
        assert "PERF FAIL bench:pyprof-overhead missing=1" in lines

    def test_absent_function_passes_trivially(self):
        sentinel = self._sentinel()
        result = self._result(0.6)
        del result["hot_functions"]["llm_d.kv_cache.score_tokens"][
            "functions"]["tracing.py:export"]
        lines, failed = sentinel.evaluate(
            self.BASELINE, {"pyprof-overhead": result})
        assert failed == 0
        assert ("PERF PASS hotfn:llm_d.kv_cache.score_tokens:"
                "tracing.py:export share=0 max=0.25") in lines

    def test_cli_exit_codes_and_grammar(self, tmp_path):
        baseline = tmp_path / "baseline.json"
        baseline.write_text(json.dumps(self.BASELINE))
        good = tmp_path / "good.json"
        good.write_text(json.dumps(self._result(0.6)))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(self._result(0.6, export_share=0.9)))

        def run(results):
            return subprocess.run(
                [sys.executable, "/root/repo/hack/perf_sentinel.py",
                 "--baseline", str(baseline),
                 "--results", f"pyprof-overhead={results}"],
                capture_output=True, text=True, timeout=60)

        ok = run(good)
        assert ok.returncode == 0
        verdicts = [l for l in ok.stdout.splitlines() if l.startswith("PERF")]
        assert len(verdicts) == 3  # bench + hotfn + OVERALL
        assert verdicts[-1].startswith("PERF OVERALL PASS")

        regressed = run(bad)
        assert regressed.returncode == 1
        assert "PERF OVERALL FAIL checks=2 failed=1" in regressed.stdout

    def test_committed_manifest_matches_a_live_overhead_result(self):
        # The committed baseline must gate every bench the Makefile
        # feeds it (perf-check runs both telemetry overhead benches),
        # with headroom wide enough that a nominal run passes — and a
        # bench missing from the results must fail, so perf-check can
        # never silently skip one.
        with open("/root/repo/benchmarking/perf_baseline.json") as f:
            manifest = json.load(f)
        assert "pyprof-overhead" in manifest["benches"]
        assert "workingset" in manifest["benches"]
        assert "controller" in manifest["benches"]
        assert "graytail" in manifest["benches"]
        assert "audit" in manifest["benches"]
        assert "fencing" in manifest["benches"]
        assert "hotpath-fleet" in manifest["benches"]
        assert "incident" in manifest["benches"]
        sentinel = self._sentinel()
        nominal = {
            "pyprof-overhead": {
                "metric": "pyprof_overhead_pct", "value": 0.08,
                "unit": "%", "vs_baseline": 1.0, "hot_functions": {}},
            "workingset": {
                "metric": "workingset_overhead_pct", "value": 0.4,
                "unit": "% of score p50", "vs_baseline": 1.0},
            "controller": {
                "metric": "flap_executed_actions", "value": 1,
                "unit": "actions", "vs_baseline": 1.0},
            "graytail": {
                "metric": "hedging_overhead_pct", "value": 0.2,
                "unit": "% of score p50", "vs_baseline": 1.0},
            "audit": {
                "metric": "audit_overhead_pct", "value": 0.6,
                "unit": "% of score p50", "vs_baseline": 1.0},
            "fencing": {
                "metric": "fence_overhead_pct", "value": 0.3,
                "unit": "% of score p50", "vs_baseline": 1.0},
            "hotpath-fleet": {
                "metric": "batched_fanout_ratio", "value": 7.0,
                "unit": "batched/per-chunk sustained GetPodScores/s ratio",
                "vs_baseline": 1.0},
            "incident": {
                "metric": "incident_trigger_overhead_pct", "value": 0.55,
                "unit": "% of score p50", "vs_baseline": 1.0},
        }
        # The nominal set must cover the whole committed manifest — a
        # bench added to the baseline without a result arm here is the
        # exact silent-skip this test exists to prevent.
        assert set(nominal) == set(manifest["benches"])
        _, failed = sentinel.evaluate(manifest, nominal)
        assert failed == 0
        missing_one = dict(nominal)
        del missing_one["workingset"]
        _, failed = sentinel.evaluate(manifest, missing_one)
        assert failed == 1  # workingset bench result went missing
