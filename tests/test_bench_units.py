"""Cheap regression cover for bench.py helpers (the slow arms run under
the driver; these keep the harness itself from rotting)."""

import json
import subprocess
import sys

sys.path.insert(0, "/root/repo")

import bench


class TestWorkload:
    def test_deterministic(self):
        import numpy as np

        a = bench.build_workload(np.random.default_rng(42), n_requests=8)
        b = bench.build_workload(np.random.default_rng(42), n_requests=8)
        assert a == b

    def test_shared_prefixes(self):
        import numpy as np

        wl = bench.build_workload(np.random.default_rng(0), n_requests=32,
                                  n_prefixes=4, prefix_len=16, suffix_len=4)
        prefixes = {tuple(p[:16]) for p in wl}
        assert len(prefixes) <= 4  # requests reuse the prefix pool
        assert all(len(p) == 20 for p in wl)


class TestQueueingTTFTs:
    def test_no_arrivals_returns_bare_service(self):
        assert bench.queueing_ttfts([1.0, 2.0], ["a", "b"], None) == [1.0, 2.0]

    def test_fifo_queue_wait_accumulates_per_pod(self):
        # Both requests hit pod "a"; the second arrives at t=0 but waits
        # for the first's service to finish.
        ttfts = bench.queueing_ttfts([1.0, 1.0], ["a", "a"], [0.0, 0.0])
        assert ttfts == [1.0, 2.0]

    def test_independent_pods_do_not_queue(self):
        ttfts = bench.queueing_ttfts([1.0, 1.0], ["a", "b"], [0.0, 0.0])
        assert ttfts == [1.0, 1.0]

    def test_idle_gap_resets_queue(self):
        # Second arrival lands after the first completes: no wait.
        ttfts = bench.queueing_ttfts([1.0, 1.0], ["a", "a"], [0.0, 5.0])
        assert ttfts == [1.0, 1.0]


class TestBenchModes:
    def test_index_bench_emits_valid_json(self):
        result = bench.bench_index_add()
        assert result["unit"] == "ns/op"
        assert result["value"] > 0
        assert result["vs_baseline"] > 0
        json.dumps(result)

    def test_python_fallback_mode(self):
        result = bench.bench_index_add(native=False)
        assert "python" in result["metric"]

    def test_cli_index_mode(self):
        out = subprocess.run(
            [sys.executable, "bench.py", "--index"],
            capture_output=True, text=True, timeout=300, cwd="/root/repo",
            env={"PATH": "/usr/bin:/bin:/opt/venv/bin"},
        )
        line = out.stdout.strip().splitlines()[-1]
        parsed = json.loads(line)
        assert set(parsed) == {"metric", "value", "unit", "vs_baseline"}


class TestGuardedLadder:
    """The driver entry's fallback ladder: probe -> device TTFT -> CPU-env
    TTFT -> index micro-bench."""

    def test_cpu_rung_strips_accelerator_env(self, monkeypatch, capsys):
        import bench

        calls = []

        def fake_ttft(env=None, timeout=900):
            calls.append(env)
            if env is None:
                return None  # device rung fails
            return '{"metric": "m", "value": 1, "unit": "%", "vs_baseline": 1}'

        monkeypatch.setattr(bench, "_accelerator_healthy", lambda: True)
        monkeypatch.setattr(bench, "_run_ttft_subprocess", fake_ttft)
        monkeypatch.setenv("PYTHONPATH", "/some/plugin")
        bench.guarded_main()
        assert capsys.readouterr().out.strip().startswith('{"metric"')
        assert calls[0] is None  # device rung ran first
        cpu_env = calls[1]
        assert "PYTHONPATH" not in cpu_env
        assert cpu_env["JAX_PLATFORMS"] == "cpu"

    def test_unhealthy_probe_skips_device_rung(self, monkeypatch, capsys):
        import bench

        calls = []

        def fake_ttft(env=None, timeout=900):
            calls.append(env)
            return '{"metric": "m", "value": 1, "unit": "%", "vs_baseline": 1}'

        monkeypatch.setattr(bench, "_accelerator_healthy", lambda: False)
        monkeypatch.setattr(bench, "_run_ttft_subprocess", fake_ttft)
        bench.guarded_main()
        assert len(calls) == 1 and calls[0] is not None  # straight to CPU

    def test_all_ttft_rungs_failing_falls_to_index_bench(self, monkeypatch, capsys):
        import json

        import bench

        monkeypatch.setattr(bench, "_accelerator_healthy", lambda: False)
        monkeypatch.setattr(bench, "_run_ttft_subprocess",
                            lambda env=None, timeout=900: None)
        bench.guarded_main()
        out = json.loads(capsys.readouterr().out.strip())
        assert "value" in out and "vs_baseline" in out
