"""Pallas prefill kernel vs the XLA paged-attention reference."""

import jax.numpy as jnp
import numpy as np
import pytest

from llmd_kv_cache_tpu.ops.kv_pages import scatter_kv_pages
from llmd_kv_cache_tpu.ops.paged_attention import paged_attention
from llmd_kv_cache_tpu.ops.pallas_paged_attention import (
    pallas_paged_prefill_attention,
)

Q_TILE = 4


def build_prefill_case(batch=2, ctx=(5, 0), new=(8, 12), q_heads=4, kv_heads=2,
                       head_dim=8, page_size=4, seed=0, dtype=jnp.float32):
    """Sequences with cached prefixes of different lengths plus new tokens
    (padded to a common q_seq)."""
    rng = np.random.default_rng(seed)
    pages_per_seq = 8
    num_pages = 1 + batch * pages_per_seq
    k_cache = jnp.zeros((num_pages, kv_heads, page_size, head_dim), dtype)
    v_cache = jnp.zeros((num_pages, kv_heads, page_size, head_dim), dtype)
    table = jnp.asarray(
        1 + np.arange(batch * pages_per_seq).reshape(batch, pages_per_seq),
        jnp.int32,
    )
    ctx_lens = jnp.asarray(ctx, jnp.int32)
    new_lens = jnp.asarray(new, jnp.int32)
    total = ctx_lens + new_lens

    max_total = pages_per_seq * page_size
    kv_all = rng.normal(size=(2, batch, max_total, kv_heads, head_dim))
    positions = jnp.arange(max_total)[None, :].repeat(batch, 0)
    valid = positions < total[:, None]
    k_cache = scatter_kv_pages(k_cache, jnp.asarray(kv_all[0], dtype), table,
                               positions, valid)
    v_cache = scatter_kv_pages(v_cache, jnp.asarray(kv_all[1], dtype), table,
                               positions, valid)

    q_seq = ((max(new) + Q_TILE - 1) // Q_TILE) * Q_TILE
    q = jnp.asarray(rng.normal(size=(batch, q_seq, q_heads, head_dim)), dtype)
    return q, k_cache, v_cache, table, ctx_lens, new_lens


@pytest.mark.parametrize("ctx,new", [((5, 0), (8, 12)), ((0, 0), (4, 4)),
                                     ((7, 3), (1, 9))])
def test_prefill_matches_reference(ctx, new):
    q, k_cache, v_cache, table, ctx_lens, new_lens = build_prefill_case(
        ctx=ctx, new=new
    )
    total = ctx_lens + new_lens
    out = pallas_paged_prefill_attention(
        q, k_cache, v_cache, table, ctx_lens, total,
        q_tile=Q_TILE, interpret=True,
    )
    q_positions = ctx_lens[:, None] + jnp.arange(q.shape[1])[None, :]
    ref = paged_attention(q, k_cache, v_cache, table, q_positions, total)

    # compare only valid (non-padded) query rows
    for b in range(q.shape[0]):
        n = int(new_lens[b])
        np.testing.assert_allclose(
            np.asarray(out[b, :n]), np.asarray(ref[b, :n]),
            rtol=2e-5, atol=2e-5,
        )


def test_prefill_gqa_bf16():
    q, k_cache, v_cache, table, ctx_lens, new_lens = build_prefill_case(
        q_heads=8, kv_heads=2, dtype=jnp.bfloat16
    )
    total = ctx_lens + new_lens
    out = pallas_paged_prefill_attention(
        q, k_cache, v_cache, table, ctx_lens, total,
        q_tile=Q_TILE, interpret=True,
    )
    q_positions = ctx_lens[:, None] + jnp.arange(q.shape[1])[None, :]
    ref = paged_attention(q, k_cache, v_cache, table, q_positions, total)
    for b in range(q.shape[0]):
        n = int(new_lens[b])
        np.testing.assert_allclose(
            np.asarray(out[b, :n], np.float32),
            np.asarray(ref[b, :n], np.float32),
            rtol=5e-2, atol=5e-2,
        )


@pytest.mark.parametrize("window", [4, 7, 16])
def test_prefill_sliding_window_matches_reference(window):
    """SWA clipping (+ out-of-window page skipping) in the prefill kernel
    matches the XLA reference's q_pos - k_pos < W convention."""
    q, k, v, table, ctx, new = build_prefill_case(ctx=(12, 0), new=(8, 12))
    total = ctx + new
    out = pallas_paged_prefill_attention(
        q, k, v, table, ctx, total,
        q_tile=Q_TILE, sliding_window=window, interpret=True,
    )
    q_seq = q.shape[1]
    q_pos = ctx[:, None] + jnp.arange(q_seq)[None, :]
    ref = paged_attention(q, k, v, table, q_pos, total, sliding_window=window)
    for b in range(q.shape[0]):
        n = int(new[b])
        np.testing.assert_allclose(
            np.asarray(out[b, :n], np.float32),
            np.asarray(ref[b, :n], np.float32), atol=2e-5, rtol=2e-5,
        )


@pytest.mark.parametrize("window,sinks", [(4, 2), (4, 5), (7, 4), (16, 1)])
def test_prefill_sinks_match_reference(window, sinks):
    """StreamingLLM sink mask in the prefill kernel: first-S positions stay
    attendable past the window; parity with the XLA mask including
    sink/window page overlaps and tiles whose window start precedes the
    sink region's end."""
    q, k, v, table, ctx, new = build_prefill_case(ctx=(12, 0), new=(8, 12))
    total = ctx + new
    out = pallas_paged_prefill_attention(
        q, k, v, table, ctx, total,
        q_tile=Q_TILE, sliding_window=window, sinks=sinks, interpret=True,
    )
    q_seq = q.shape[1]
    q_pos = ctx[:, None] + jnp.arange(q_seq)[None, :]
    ref = paged_attention(q, k, v, table, q_pos, total, sliding_window=window,
                          attention_sinks=sinks)
    for b in range(q.shape[0]):
        n = int(new[b])
        np.testing.assert_allclose(
            np.asarray(out[b, :n], np.float32),
            np.asarray(ref[b, :n], np.float32), atol=2e-5, rtol=2e-5,
        )


@pytest.mark.parametrize("kpb", [1, 3])
def test_prefill_pages_per_block_variants(kpb):
    """Superblock streaming matches the single-page path, including
    partial trailing superblocks and window-skipped prefixes."""
    q, k, v, table, ctx, new = build_prefill_case(ctx=(12, 0), new=(8, 12))
    total = ctx + new
    ref = pallas_paged_prefill_attention(
        q, k, v, table, ctx, total, q_tile=Q_TILE, sliding_window=7,
        sinks=4, pages_per_block=1, interpret=True)
    out = pallas_paged_prefill_attention(
        q, k, v, table, ctx, total, q_tile=Q_TILE, sliding_window=7,
        sinks=4, pages_per_block=kpb, interpret=True)
    for b in range(q.shape[0]):
        n = int(new[b])
        np.testing.assert_allclose(
            np.asarray(out[b, :n], np.float32),
            np.asarray(ref[b, :n], np.float32), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("kpb", [1, 3])
def test_prefill_shared_kv_single_stream(kpb):
    """shared_kv=True (absorbed MLA: values ARE the latent keys) streams
    each page once and reuses the K scratch as values — bit-identical to
    the double-stream aliased path, including partial superblocks."""
    q, k, _v, table, ctx, new = build_prefill_case(
        ctx=(5, 0), new=(8, 12), kv_heads=1, q_heads=4)
    total = ctx + new
    ref = pallas_paged_prefill_attention(
        q, k, k, table, ctx, total, q_tile=Q_TILE, pages_per_block=kpb,
        interpret=True)
    out = pallas_paged_prefill_attention(
        q, k, k, table, ctx, total, q_tile=Q_TILE, pages_per_block=kpb,
        shared_kv=True, interpret=True)
    for b in range(q.shape[0]):
        n = int(new[b])
        np.testing.assert_array_equal(np.asarray(out[b, :n]),
                                      np.asarray(ref[b, :n]))


def test_prefill_window_larger_than_context_equals_full():
    q, k, v, table, ctx, new = build_prefill_case()
    total = ctx + new
    full = pallas_paged_prefill_attention(
        q, k, v, table, ctx, total, q_tile=Q_TILE, interpret=True)
    windowed = pallas_paged_prefill_attention(
        q, k, v, table, ctx, total, q_tile=Q_TILE, sliding_window=10_000,
        interpret=True)
    np.testing.assert_allclose(np.asarray(windowed, np.float32),
                               np.asarray(full, np.float32),
                               atol=1e-6, rtol=1e-6)
