"""Paged-Llama model and ops tests (CPU backend, 8 virtual devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmd_kv_cache_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_kv_cache,
    init_params,
)
from llmd_kv_cache_tpu.ops.kv_pages import gather_kv_pages, scatter_kv_pages
from llmd_kv_cache_tpu.ops.paged_attention import paged_attention


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(jax.random.PRNGKey(0), cfg)


class TestKVPages:
    def test_scatter_gather_roundtrip(self):
        cache = jnp.zeros((8, 2, 4, 4), jnp.float32)
        new = jnp.arange(2 * 8 * 2 * 4, dtype=jnp.float32).reshape(2, 8, 2, 4)
        table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        positions = jnp.arange(8)[None, :].repeat(2, axis=0)
        valid = jnp.ones((2, 8), bool)
        cache = scatter_kv_pages(cache, new, table, positions, valid)
        out = gather_kv_pages(cache, table)
        np.testing.assert_allclose(np.asarray(out), np.asarray(new))

    def test_invalid_slots_go_to_garbage(self):
        cache = jnp.zeros((4, 1, 4, 2), jnp.float32)
        new = jnp.ones((1, 4, 1, 2), jnp.float32)
        table = jnp.asarray([[2]], jnp.int32)
        positions = jnp.arange(4)[None, :]
        valid = jnp.asarray([[True, True, False, False]])
        cache = scatter_kv_pages(cache, new, table, positions, valid)
        page2 = np.asarray(cache[2])  # [kv_heads, page_size, head_dim]
        assert page2[:, :2].sum() == 4  # two valid slots written
        assert page2[:, 2:].sum() == 0  # invalid slots untouched
        assert np.asarray(cache[0]).sum() != 0  # garbage page absorbed them


class TestPagedAttention:
    def test_matches_dense_attention(self):
        """Paged attention == plain causal attention on contiguous pages."""
        rng = np.random.default_rng(0)
        b, s, h, d, page = 2, 8, 2, 4, 4
        q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)

        # scatter k/v into pages 1..4 (per sequence)
        k_cache = jnp.zeros((16, h, page, d), jnp.float32)
        v_cache = jnp.zeros((16, h, page, d), jnp.float32)
        table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        positions = jnp.arange(s)[None, :].repeat(b, axis=0)
        valid = jnp.ones((b, s), bool)
        k_cache = scatter_kv_pages(k_cache, k, table, positions, valid)
        v_cache = scatter_kv_pages(v_cache, v, table, positions, valid)

        out = paged_attention(
            q, k_cache, v_cache, table, positions, jnp.full((b,), s, jnp.int32)
        )

        # dense reference
        scale = d ** -0.5
        logits = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), v)

        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_gqa_grouping(self):
        b, s, qh, kvh, d, page = 1, 4, 4, 2, 4, 4
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(b, s, qh, d)), jnp.float32)
        k_cache = jnp.asarray(rng.normal(size=(4, page, kvh, d)), jnp.float32)
        v_cache = jnp.asarray(rng.normal(size=(4, page, kvh, d)), jnp.float32)
        table = jnp.asarray([[1]], jnp.int32)
        positions = jnp.arange(s)[None, :]
        out = paged_attention(
            q, k_cache, v_cache, table, positions, jnp.asarray([s], jnp.int32)
        )
        assert out.shape == (b, s, qh, d)


class TestForward:
    def test_prefill_then_decode_matches_full_prefill(self, cfg, params):
        """KV correctness: logits for token N computed incrementally equal
        logits from prefilling all N+1 tokens at once."""
        prompt = np.asarray([[5, 7, 9, 11, 13, 17, 19, 23]], np.int32)
        table = jnp.asarray([[1, 2, 3, 4]], jnp.int32)

        # full prefill of 8 tokens
        k1, v1 = init_kv_cache(cfg, 8)
        logits_full, k1, v1 = forward(
            params, cfg, jnp.asarray(prompt), k1, v1, table,
            jnp.asarray([0], jnp.int32), jnp.asarray([8], jnp.int32),
        )

        # prefill 7, then decode token 8
        k2, v2 = init_kv_cache(cfg, 8)
        _, k2, v2 = forward(
            params, cfg, jnp.asarray(prompt[:, :7]), k2, v2, table,
            jnp.asarray([0], jnp.int32), jnp.asarray([7], jnp.int32),
        )
        logits_step, k2, v2 = forward(
            params, cfg, jnp.asarray(prompt[:, 7:8]), k2, v2, table,
            jnp.asarray([7], jnp.int32), jnp.asarray([1], jnp.int32),
        )

        np.testing.assert_allclose(
            np.asarray(logits_full[0, 7]), np.asarray(logits_step[0, 0]),
            rtol=3e-2, atol=3e-2,  # bf16 accumulation tolerance
        )

    def test_padding_does_not_affect_logits(self, cfg, params):
        prompt = np.asarray([[5, 7, 9, 11]], np.int32)
        padded = np.asarray([[5, 7, 9, 11, 0, 0, 0, 0]], np.int32)
        table = jnp.asarray([[1, 2, 3, 4]], jnp.int32)

        k1, v1 = init_kv_cache(cfg, 8)
        logits_a, *_ = forward(
            params, cfg, jnp.asarray(prompt), k1, v1, table,
            jnp.asarray([0], jnp.int32), jnp.asarray([4], jnp.int32),
        )
        k2, v2 = init_kv_cache(cfg, 8)
        logits_b, *_ = forward(
            params, cfg, jnp.asarray(padded), k2, v2, table,
            jnp.asarray([0], jnp.int32), jnp.asarray([4], jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(logits_a[0, 3]), np.asarray(logits_b[0, 3]), rtol=1e-5
        )
