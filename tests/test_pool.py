"""Event pool semantics tests, driven against a real in-memory index.

Mirrors the reference ``pool_test.go`` approach: build parsed event batches
and run them through ``process_event_batch`` / the full sharded pool.
"""

import msgpack
import pytest

from llmd_kv_cache_tpu.core import ChunkedTokenDatabase, PodEntry, TokenProcessorConfig
from llmd_kv_cache_tpu.events import (
    AllBlocksClearedEvent,
    BlockRemovedEvent,
    BlockStoredEvent,
    EventBatch,
    Pool,
    PoolConfig,
    RawMessage,
)
from llmd_kv_cache_tpu.events.pool import realign_extra_features
from llmd_kv_cache_tpu.core.extra_keys import BlockExtraFeatures
from llmd_kv_cache_tpu.index import InMemoryIndex, InMemoryIndexConfig

BLOCK = 4  # canonical block size for tests
MODEL = "model-a"
POD = "pod-1"


@pytest.fixture
def processor():
    return ChunkedTokenDatabase(TokenProcessorConfig(block_size_tokens=BLOCK))


@pytest.fixture
def index():
    return InMemoryIndex(InMemoryIndexConfig(size=10_000))


@pytest.fixture
def pool(index, processor):
    return Pool(PoolConfig(concurrency=2), index, processor)


def batch(*events, ts=1.0, dp=None):
    return EventBatch(timestamp=ts, events=list(events), data_parallel_rank=dp)


def stored(hashes, tokens, parent=0, block_size=BLOCK, **kw):
    return BlockStoredEvent(
        block_hashes=hashes, tokens=tokens, parent_hash=parent, block_size=block_size, **kw
    )


class TestBlockStored:
    def test_basic_ingest(self, pool, index, processor):
        tokens = list(range(8))
        pool.process_event_batch(batch(stored([101, 102], tokens)), POD, MODEL)
        request_keys = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        result = index.lookup(request_keys)
        assert set(result) == set(request_keys)
        assert result[request_keys[0]] == [PodEntry(POD, "tpu-hbm")]
        # engine→request mapping learned
        assert index.get_request_key(101) == request_keys[0]
        assert index.get_request_key(102) == request_keys[1]

    def test_default_tier_is_tpu_hbm(self, pool, index, processor):
        pool.process_event_batch(batch(stored([1], list(range(4)))), POD, MODEL)
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        assert index.lookup(rk)[rk[0]][0].device_tier == "tpu-hbm"

    def test_explicit_tier_lowercased(self, pool, index, processor):
        pool.process_event_batch(
            batch(stored([1], list(range(4)), device_tier="CPU")), POD, MODEL
        )
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        assert index.lookup(rk)[rk[0]][0].device_tier == "cpu"

    def test_parent_chain_resolution(self, pool, index, processor):
        t1, t2 = list(range(4)), list(range(4, 8))
        pool.process_event_batch(batch(stored([11], t1)), POD, MODEL)
        # second event chains via engine parent hash 11
        pool.process_event_batch(batch(stored([12], t2, parent=11)), POD, MODEL)
        full_keys = processor.tokens_to_kv_block_keys(0, t1 + t2, MODEL)
        result = index.lookup(full_keys)
        assert set(result) == set(full_keys)

    def test_unknown_parent_drops_event(self, pool, index, processor):
        pool.process_event_batch(
            batch(stored([12], list(range(4)), parent=999)), POD, MODEL
        )
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        assert index.lookup(rk) == {}

    def test_lora_name_overrides_model(self, pool, index, processor):
        tokens = list(range(4))
        pool.process_event_batch(
            batch(stored([1], tokens, lora_name="my-lora")), POD, MODEL
        )
        lora_keys = processor.tokens_to_kv_block_keys(0, tokens, "my-lora")
        base_keys = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        assert index.lookup(lora_keys) != {}
        assert index.lookup(base_keys) == {}

    def test_group_learning(self, pool, index, processor):
        pool.process_event_batch(
            batch(
                stored(
                    [1], list(range(4)), group_idx=2,
                    kv_cache_spec_kind="sliding_window",
                    kv_cache_spec_sliding_window=512,
                )
            ),
            POD, MODEL,
        )
        meta = pool.group_catalog.get(POD, 2)
        assert meta is not None
        assert meta.kind == "sliding_window"
        assert meta.sliding_window_size == 512
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        entry = index.lookup(rk)[rk[0]][0]
        assert entry.has_group and entry.group_idx == 2

    def test_many_to_one_engine_keys(self, pool, index, processor):
        """Engine block size 2, canonical 4: two engine keys per request key."""
        tokens = list(range(8))
        pool.process_event_batch(
            batch(stored([1, 2, 3, 4], tokens, block_size=2)), POD, MODEL
        )
        request_keys = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        assert index.get_request_key(1) == request_keys[0]
        assert index.get_request_key(2) == request_keys[0]
        assert index.get_request_key(3) == request_keys[1]
        assert index.get_request_key(4) == request_keys[1]

    def test_extra_keys_taint(self, pool, index, processor):
        tokens = list(range(4))
        pool.process_event_batch(
            batch(stored([1], tokens, extra_keys=[["mmh"]])), POD, MODEL
        )
        plain_keys = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        tainted_keys = processor.tokens_to_kv_block_keys(
            0, tokens, MODEL, [BlockExtraFeatures(mm_hashes=["mmh"])]
        )
        assert index.lookup(plain_keys) == {}
        assert index.lookup(tainted_keys) != {}


class TestDeviceTierUpdate:
    def test_tokenless_stored_adds_tier(self, pool, index, processor):
        tokens = list(range(8))
        pool.process_event_batch(batch(stored([21, 22], tokens)), POD, MODEL)
        # offload event: same engine keys, no tokens, storage tier
        pool.process_event_batch(
            batch(stored([21, 22], [], device_tier="SHARED_STORAGE")), POD, MODEL
        )
        rks = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        result = index.lookup(rks)
        tiers0 = {e.device_tier for e in result[rks[0]]}
        assert tiers0 == {"tpu-hbm", "shared_storage"}

    def test_tokenless_unknown_keys_noop(self, pool, index):
        pool.process_event_batch(
            batch(stored([777], [], device_tier="SHARED_STORAGE")), POD, MODEL
        )
        # nothing indexed, nothing crashes

    def test_partial_block_skipped(self, pool, index, processor):
        """Events with 0 < tokens < block size must not become tier updates."""
        pool.process_event_batch(batch(stored([31], list(range(4)))), POD, MODEL)
        pool.process_event_batch(
            batch(stored([31], [1, 2], device_tier="CPU")), POD, MODEL
        )
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        tiers = {e.device_tier for e in index.lookup(rk)[rk[0]]}
        assert tiers == {"tpu-hbm"}


class TestRemoveAndClear:
    def test_block_removed(self, pool, index, processor):
        tokens = list(range(4))
        pool.process_event_batch(batch(stored([41], tokens)), POD, MODEL)
        pool.process_event_batch(
            batch(BlockRemovedEvent(block_hashes=[41])), POD, MODEL
        )
        rk = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        assert index.lookup(rk) == {}

    def test_remove_only_matching_tier(self, pool, index, processor):
        tokens = list(range(4))
        pool.process_event_batch(batch(stored([42], tokens)), POD, MODEL)
        pool.process_event_batch(
            batch(stored([42], [], device_tier="CPU")), POD, MODEL
        )
        # remove the HBM copy; CPU copy must survive
        pool.process_event_batch(
            batch(BlockRemovedEvent(block_hashes=[42])), POD, MODEL
        )
        rk = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        tiers = {e.device_tier for e in index.lookup(rk)[rk[0]]}
        assert tiers == {"cpu"}

    def test_all_blocks_cleared(self, pool, index, processor):
        tokens = list(range(8))
        other_tokens = list(range(100, 104))
        pool.process_event_batch(batch(stored([51, 52], tokens)), POD, MODEL)
        pool.process_event_batch(batch(stored([61], other_tokens)), "pod-2", MODEL)
        pool.process_event_batch(batch(AllBlocksClearedEvent()), POD, MODEL)
        rks = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        assert index.lookup(rks) == {}
        rk2 = processor.tokens_to_kv_block_keys(0, other_tokens, MODEL)
        assert index.lookup(rk2) != {}  # other pod untouched


class TestDPRank:
    def test_dp_rank_ignored_by_default(self, pool, index, processor):
        pool.process_event_batch(batch(stored([1], list(range(4))), dp=3), POD, MODEL)
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        assert index.lookup(rk)[rk[0]][0].pod_identifier == POD

    def test_dp_rank_tracked_when_enabled(self, index, processor):
        pool = Pool(PoolConfig(concurrency=1, track_dp_rank=True), index, processor)
        pool.process_event_batch(batch(stored([1], list(range(4))), dp=3), POD, MODEL)
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        assert index.lookup(rk)[rk[0]][0].pod_identifier == f"{POD}|dp3"


class TestRealignExtraFeatures:
    def test_passthrough_when_equal(self):
        f = [BlockExtraFeatures(["a"])]
        assert realign_extra_features(f, 1) is f

    def test_one_to_many_replicates(self):
        f = [BlockExtraFeatures(["a"]), None]
        out = realign_extra_features(f, 4)
        assert out[0].mm_hashes == ["a"]
        assert out[1].mm_hashes == ["a"]
        assert out[2] is None and out[3] is None

    def test_many_to_one_merges(self):
        f = [BlockExtraFeatures(["a"]), BlockExtraFeatures(["b"]),
             None, BlockExtraFeatures(["c"])]
        out = realign_extra_features(f, 2)
        assert out[0].mm_hashes == ["a", "b"]
        assert out[1].mm_hashes == ["c"]

    def test_zero_canonical(self):
        assert realign_extra_features([BlockExtraFeatures(["a"])], 0) is None


class TestShardedPoolThreads:
    def test_full_pipeline_via_raw_messages(self, index, processor):
        """Raw msgpack messages through the sharded thread pool."""
        pool = Pool(PoolConfig(concurrency=4), index, processor)
        pool.start()
        try:
            tokens = list(range(8))
            ev = ["BlockStored", [71, 72], None, tokens, BLOCK]
            payload = msgpack.packb([1.0, [ev]], use_bin_type=True)
            pool.add_task(RawMessage(topic=f"kv@{POD}@{MODEL}", sequence=0, payload=payload))
            pool.join()
            rks = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
            assert set(index.lookup(rks)) == set(rks)
        finally:
            pool.shutdown()

    def test_same_pod_same_shard_ordering(self, index, processor):
        """Store→remove sequences for one pod retain order across 4 shards."""
        pool = Pool(PoolConfig(concurrency=4), index, processor)
        pool.start()
        try:
            tokens = list(range(4))
            for i in range(50):
                stored_ev = ["BlockStored", [1000 + i], None, tokens, BLOCK]
                removed_ev = ["BlockRemoved", [1000 + i]]
                pool.add_task(RawMessage(
                    topic=f"kv@{POD}@{MODEL}", sequence=2 * i,
                    payload=msgpack.packb([1.0, [stored_ev]], use_bin_type=True)))
                pool.add_task(RawMessage(
                    topic=f"kv@{POD}@{MODEL}", sequence=2 * i + 1,
                    payload=msgpack.packb([1.0, [removed_ev]], use_bin_type=True)))
            pool.join()
            rk = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
            # every store was followed by its remove, in order → empty index
            assert index.lookup(rk) == {}
        finally:
            pool.shutdown()

    def test_malformed_message_does_not_kill_worker(self, index, processor):
        pool = Pool(PoolConfig(concurrency=1), index, processor)
        pool.start()
        try:
            pool.add_task(RawMessage(topic="kv@p@m", sequence=0, payload=b"garbage"))
            tokens = list(range(4))
            ev = ["BlockStored", [81], None, tokens, BLOCK]
            pool.add_task(RawMessage(
                topic=f"kv@{POD}@{MODEL}", sequence=1,
                payload=msgpack.packb([1.0, [ev]], use_bin_type=True)))
            pool.join()
            rk = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
            assert index.lookup(rk) != {}
        finally:
            pool.shutdown()
