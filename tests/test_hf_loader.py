"""HF weight loading: logits parity against the transformers reference.

A random-init HF ``LlamaForCausalLM`` is the authoritative oracle: our
paged forward over the converted weights must reproduce its logits (fp32,
tight tolerance). This pins the model family to the upstream
implementation — RoPE convention, RMSNorm placement/eps, SwiGLU order,
GQA head grouping — not just to internal oracles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from llmd_kv_cache_tpu.models.hf_loader import config_from_hf, params_from_hf
from llmd_kv_cache_tpu.models.llama import forward, init_kv_cache


def _build_hf(vocab=256, hidden=64, inter=128, layers=2, heads=4, kv=2,
              hd=16, tie=False, window=None, seed=0):
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM
    from transformers import MistralConfig, MistralForCausalLM

    torch.manual_seed(seed)
    if window is not None:
        hf_cfg = MistralConfig(
            vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
            num_hidden_layers=layers, num_attention_heads=heads,
            num_key_value_heads=kv, head_dim=hd, rms_norm_eps=1e-5,
            rope_theta=10000.0, sliding_window=window,
            tie_word_embeddings=tie)
        model = MistralForCausalLM(hf_cfg)
    else:
        hf_cfg = HFLlamaConfig(
            vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
            num_hidden_layers=layers, num_attention_heads=heads,
            num_key_value_heads=kv, head_dim=hd, rms_norm_eps=1e-5,
            rope_theta=10000.0, attention_bias=False, mlp_bias=False,
            tie_word_embeddings=tie)
        model = LlamaForCausalLM(hf_cfg)
    return hf_cfg, model.eval()


def _our_logits(cfg, params, tokens):
    n = len(tokens)
    page_size = cfg.page_size
    pages = (n + page_size - 1) // page_size + 1
    tok = jnp.zeros((1, ((n + page_size - 1) // page_size) * page_size),
                    jnp.int32).at[0, :n].set(jnp.asarray(tokens))
    table = jnp.asarray(1 + np.arange(pages)[None, :], jnp.int32)
    k, v = init_kv_cache(cfg, pages + 2)
    logits, _, _ = forward(params, cfg, tok, k, v, table,
                           jnp.asarray([0], jnp.int32),
                           jnp.asarray([n], jnp.int32))
    return np.asarray(logits[0, :n], np.float32)


@pytest.mark.parametrize("tie", [False, True])
def test_llama_logits_match_transformers(tie):
    hf_cfg, model = _build_hf(tie=tie)
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    params = params_from_hf(model.state_dict(), cfg)

    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 250, 21).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    # Greedy continuations agree everywhere, not just within tolerance.
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_mistral_sliding_window_matches_transformers():
    """Mistral = Llama arch + uniform SWA: the window mask must match HF's
    (prompt longer than the window so it actually clips)."""
    hf_cfg, model = _build_hf(window=8)
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.sliding_window == 8 and len(cfg.swa_layers) == cfg.num_layers
    params = params_from_hf(model.state_dict(), cfg)

    rng = np.random.default_rng(1)
    tokens = rng.integers(1, 250, 20).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_qwen3_qk_norm_matches_transformers():
    """Qwen3 = GQA + per-head RMS on Q/K pre-RoPE; the loader maps
    q_norm/k_norm and the parity must hold through them."""
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(5)
    hf_cfg = Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False)
    model = Qwen3ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.qk_norm
    params = params_from_hf(model.state_dict(), cfg)
    assert "q_norm" in params["layers"][0]

    rng = np.random.default_rng(4)
    tokens = rng.integers(1, 250, 18).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_qwen2_partial_window_layer_types():
    """max_window_layers → layer_types: first-N layers full attention,
    rest SWA. The converted config must mirror the hybrid layout, and
    logits must match HF for prompts longer than the window."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(6)
    hf_cfg = Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0, sliding_window=8,
        use_sliding_window=True, max_window_layers=2,
        tie_word_embeddings=False)
    model = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.swa_layers == (2, 3) and cfg.sliding_window == 8
    assert cfg.is_hybrid
    params = params_from_hf(model.state_dict(), cfg)

    rng = np.random.default_rng(5)
    tokens = rng.integers(1, 250, 20).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    # Hybrid configs use the two-pool forward; drive it directly.
    from llmd_kv_cache_tpu.models.llama import (
        forward_hybrid, init_kv_cache_hybrid)

    n = len(tokens)
    pad = ((n + 3) // 4) * 4
    tok = jnp.zeros((1, pad), jnp.int32).at[0, :n].set(jnp.asarray(tokens))
    pages = pad // 4 + 1
    table = jnp.asarray(1 + np.arange(pages)[None, :], jnp.int32)
    k0, v0, k1, v1 = init_kv_cache_hybrid(cfg, pages + 2, pages + 2)
    logits, *_ = forward_hybrid(
        params, cfg, tok, k0, v0, k1, v1, table, table,
        jnp.asarray([0], jnp.int32), jnp.asarray([n], jnp.int32))
    ours = np.asarray(logits[0, :n], np.float32)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_llama3_rope_scaling_matches_transformers():
    """Llama-3.1's frequency-band NTK rope scaling: our per-band freq
    transform must reproduce HF's logits at positions deep enough that
    the scaled bands actually matter (orig_max=32 with a 48-token
    prompt crosses it)."""
    from transformers import LlamaConfig as HFLlamaConfig
    from transformers import LlamaForCausalLM

    torch.manual_seed(12)
    hf_cfg = HFLlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rms_norm_eps=1e-5, rope_theta=10000.0,
        max_position_embeddings=256, attention_bias=False, mlp_bias=False,
        tie_word_embeddings=False,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32})
    model = LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.rope_scaling[0] == "llama3"
    params = params_from_hf(model.state_dict(), cfg)

    rng = np.random.default_rng(11)
    tokens = rng.integers(1, 250, 48).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_yarn_rope_scaling_matches_transformers():
    """Yarn (NTK-by-parts) scaling with an inferred attention factor:
    frequency blend + cos/sin scaling must match HF at positions past
    the original max."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(15)
    hf_cfg = Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0, use_sliding_window=False,
        max_position_embeddings=512, tie_word_embeddings=False,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 32})
    model = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.rope_scaling[0] == "yarn" and cfg.rope_scaling[5] > 1.0
    params = params_from_hf(model.state_dict(), cfg)

    rng = np.random.default_rng(15)
    tokens = rng.integers(1, 250, 48).tolist()  # crosses orig_max=32
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_unsupported_features_raise():
    """rope_scaling / projection biases / MoE must refuse loudly instead
    of converting to silently-wrong logits."""
    from transformers import LlamaConfig as HFLlamaConfig

    base = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
                num_hidden_layers=1, num_attention_heads=2,
                num_key_value_heads=2)
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        config_from_hf(HFLlamaConfig(
            **base, rope_scaling={"rope_type": "linear", "factor": 2.0}))
    with pytest.raises(NotImplementedError, match="bias"):
        config_from_hf(HFLlamaConfig(**base, mlp_bias=True))
    with pytest.raises(NotImplementedError, match="model_type"):
        config_from_hf(type("G", (), dict(
            HFLlamaConfig(**base).to_dict(), model_type="gemma2",
            num_hidden_layers=1))())
    # Tensors with no slot in this model (o_proj bias, extra norms) are
    # rejected at the state dict, even when the config did not declare
    # them — QKV biases (Qwen2 lineage) are the supported exception.
    hf_cfg, model = _build_hf(vocab=64, hidden=32, inter=64, layers=1,
                              heads=2, kv=2, hd=16)
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    for extra in ("model.layers.0.self_attn.o_proj.bias",
                  "model.layers.0.pre_feedforward_layernorm.weight"):
        sd = dict(model.state_dict())
        sd[extra] = torch.zeros(32)
        with pytest.raises(NotImplementedError, match="unmapped|bias"):
            params_from_hf(sd, cfg)


def test_qwen2_tp_serve_with_biases():
    """QKV biases shard column-parallel under tp (bias splits with its
    output dim); the tp-served tokens must match single-device."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
    from llmd_kv_cache_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    torch.manual_seed(8)
    hf_cfg = Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0, use_sliding_window=False,
        tie_word_embeddings=False)
    model = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    params = params_from_hf(model.state_dict(), cfg)
    assert "bq" in params["layers"][0]

    prompt = np.random.default_rng(6).integers(1, 250, 16).tolist()

    def serve(mesh):
        return MiniEngine(
            EngineConfig(model=cfg, num_pages=64, max_pages_per_seq=16,
                         model_name="q2", pod_identifier="p"),
            params=params, mesh=mesh).generate("r", prompt,
                                               max_new_tokens=6)

    ref = serve(None)
    assert serve(make_mesh({"tp": 2}, jax.devices()[:2])) == ref


def test_deepseek_q_lora_matches_transformers():
    """The full V2/V3 form: q down-projected to a compressed latent,
    RMS-normed, up-projected per head — parity through the q-LoRA path."""
    from transformers import DeepseekV3Config, DeepseekV3ForCausalLM

    torch.manual_seed(14)
    hf_cfg = DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, q_lora_rank=24, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=16, v_head_dim=16,
        rms_norm_eps=1e-5, rope_theta=10000.0, first_k_dense_replace=2,
        tie_word_embeddings=False)
    model = DeepseekV3ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    params = params_from_hf(
        model.state_dict(), cfg,
        mla_rope_interleaved=getattr(hf_cfg, "rope_interleave", True))
    assert "w_dq" in params["layers"][0]

    rng = np.random.default_rng(13)
    tokens = rng.integers(1, 250, 18).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_deepseek_v2_yarn_matches_transformers():
    """In-tree DeepseekV2Attention applies NO mscale^2 softmax term
    (unlike V3) — a V2+yarn conversion must set softmax_scale_mult=1 and
    still match HF logits."""
    from transformers import DeepseekV2Config, DeepseekV2ForCausalLM

    torch.manual_seed(18)
    hf_cfg = DeepseekV2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, q_lora_rank=None, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=16, v_head_dim=16,
        rms_norm_eps=1e-5, rope_theta=10000.0, first_k_dense_replace=2,
        max_position_embeddings=512, tie_word_embeddings=False,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "mscale": 0.707, "mscale_all_dim": 0.707,
                      "original_max_position_embeddings": 32})
    model = DeepseekV2ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.softmax_scale_mult == 1.0
    params = params_from_hf(
        model.state_dict(), cfg,
        mla_rope_interleaved=getattr(hf_cfg, "rope_interleave", True))

    rng = np.random.default_rng(18)
    tokens = rng.integers(1, 250, 44).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_qwen3_moe_matches_transformers():
    """Qwen3-MoE (the A3B lineage): Mixtral-style routed experts with
    norm_topk_prob=False — weights are the top-k entries of the FULL
    softmax, unnormalized — plus QK-norm and an mlp_only dense layer."""
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    torch.manual_seed(21)
    hf_cfg = Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rms_norm_eps=1e-5, rope_theta=10000.0,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        moe_intermediate_size=32, decoder_sparse_step=1,
        mlp_only_layers=[0], tie_word_embeddings=False,
        use_sliding_window=False)
    model = Qwen3MoeForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.moe_layers == (1, 2)
    assert cfg.moe_router == ("softmax_topk", 0) and cfg.qk_norm
    params = params_from_hf(model.state_dict(), cfg)
    assert "router" not in params["layers"][0]
    assert "router_bias" not in params["layers"][1]  # no DeepSeek bias

    rng = np.random.default_rng(21)
    tokens = rng.integers(1, 250, 19).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_qwen3_moe_norm_topk_matches_transformers():
    """The production Qwen3-MoE config (norm_topk_prob=True, as released
    A3B checkpoints ship): renormalized top-k weights through the
    softmax_topk dispatch."""
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    torch.manual_seed(22)
    hf_cfg = Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, num_experts=4, num_experts_per_tok=2,
        norm_topk_prob=True, moe_intermediate_size=32,
        decoder_sparse_step=1, mlp_only_layers=[],
        tie_word_embeddings=False, use_sliding_window=False)
    model = Qwen3MoeForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.moe_router == ("softmax_topk", 1)
    params = params_from_hf(model.state_dict(), cfg)

    rng = np.random.default_rng(22)
    tokens = rng.integers(1, 250, 17).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_deepseek_moe_matches_transformers():
    """The full DeepSeek-V3 MoE: sigmoid scoring, e_score_correction-
    biased group-limited top-k selection (weights from UNBIASED scores),
    routed scaling, shared expert, and the dense-first_k mixed layout —
    all against the in-tree DeepseekV3MoE, with a non-zero correction
    bias so the biased-selection path demonstrably engages."""
    from transformers import DeepseekV3Config, DeepseekV3ForCausalLM

    torch.manual_seed(19)
    hf_cfg = DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=4, q_lora_rank=None, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=16, v_head_dim=16,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        first_k_dense_replace=1, n_routed_experts=8,
        num_experts_per_tok=2, n_group=4, topk_group=2,
        norm_topk_prob=True, routed_scaling_factor=2.5,
        n_shared_experts=1, moe_intermediate_size=32,
        tie_word_embeddings=False)
    model = DeepseekV3ForCausalLM(hf_cfg).eval()
    with torch.no_grad():  # engage the bias-corrected selection path
        for li in (1, 2):
            model.model.layers[li].mlp.gate.e_score_correction_bias.copy_(
                torch.randn(8) * 0.5)
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.moe_layers == (1, 2) and cfg.moe_router[0] == "deepseek_v3"
    params = params_from_hf(
        model.state_dict(), cfg,
        mla_rope_interleaved=getattr(hf_cfg, "rope_interleave", True))
    assert "router" not in params["layers"][0]  # dense first layer
    assert "w_gate_sh" in params["layers"][1]

    rng = np.random.default_rng(19)
    tokens = rng.integers(1, 250, 21).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_deepseek_yarn_matches_transformers():
    """DeepSeek's yarn: generic NTK-by-parts on the decoupled rope dims
    PLUS mscale^2 folded into the softmax scale (mscale_all_dim) — both
    must match the in-tree DeepseekV3Attention at positions past the
    original max."""
    from transformers import DeepseekV3Config, DeepseekV3ForCausalLM

    torch.manual_seed(16)
    hf_cfg = DeepseekV3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, q_lora_rank=24, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=16, v_head_dim=16,
        rms_norm_eps=1e-5, rope_theta=10000.0, first_k_dense_replace=2,
        max_position_embeddings=512, tie_word_embeddings=False,
        rope_scaling={"rope_type": "yarn", "factor": 4.0, "mscale": 1.0,
                      "mscale_all_dim": 1.0, "beta_fast": 32,
                      "beta_slow": 1,
                      "original_max_position_embeddings": 32})
    model = DeepseekV3ForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.rope_scaling[0] == "yarn" and cfg.softmax_scale_mult > 1.0
    params = params_from_hf(
        model.state_dict(), cfg,
        mla_rope_interleaved=getattr(hf_cfg, "rope_interleave", True))

    rng = np.random.default_rng(16)
    tokens = rng.integers(1, 250, 48).tolist()  # crosses orig_max=32
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


@pytest.mark.parametrize("which", ["v2", "v3"])
def test_deepseek_mla_matches_transformers(which):
    """The STRONG MLA oracle: our absorbed attention (latent-only cache,
    up-projections folded into q and the output) must reproduce HF's
    materialized MLA logits — a cross-implementation check of the
    absorption algebra, the kv_a_layernorm placement, and the
    interleaved→half-split rotary weight permutation."""
    if which == "v2":
        from transformers import DeepseekV2Config as DSConfig
        from transformers import DeepseekV2ForCausalLM as DSModel
    else:
        from transformers import DeepseekV3Config as DSConfig
        from transformers import DeepseekV3ForCausalLM as DSModel

    torch.manual_seed(7)
    hf_cfg = DSConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4, q_lora_rank=None, kv_lora_rank=16,
        qk_rope_head_dim=8, qk_nope_head_dim=16, v_head_dim=16,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        first_k_dense_replace=2,  # all layers dense: no MoE weights
        tie_word_embeddings=False)
    model = DSModel(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.is_mla and cfg.kv_lora_rank == 16
    params = params_from_hf(
        model.state_dict(), cfg,
        mla_rope_interleaved=getattr(hf_cfg, "rope_interleave", True))
    assert "latent_norm" in params["layers"][0]

    rng = np.random.default_rng(8)
    tokens = rng.integers(1, 250, 19).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_mixtral_moe_matches_transformers():
    """Mixtral block-sparse MoE: the exact 'dense' dispatch (one-hot
    top-k mix) must reproduce HF's routed expert outputs — top-k→softmax
    here equals HF's softmax→top-k→renorm."""
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(9)
    hf_cfg = MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False)
    model = MixtralForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    assert cfg.num_experts == 4 and cfg.moe_dispatch == "dense"
    params = params_from_hf(model.state_dict(), cfg)
    assert params["layers"][0]["w_gate"].shape == (4, 64, 128)

    rng = np.random.default_rng(9)
    tokens = rng.integers(1, 250, 17).tolist()
    with torch.no_grad():
        ref = model(torch.tensor([tokens])).logits[0].float().numpy()
    ours = _our_logits(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    np.testing.assert_array_equal(ours.argmax(-1), ref.argmax(-1))


def test_load_checkpoint_directory_roundtrip(tmp_path):
    """The disk path: save_pretrained (safetensors) → load_hf_checkpoint
    → serve. Covers AutoConfig/AutoModel materialization, the dtype-auto
    load, and the rope_interleave plumbing end-to-end."""
    from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
    from llmd_kv_cache_tpu.models.hf_loader import load_hf_checkpoint

    hf_cfg, model = _build_hf(seed=10)
    ckpt = tmp_path / "ckpt"
    model.save_pretrained(ckpt)
    cfg, params = load_hf_checkpoint(str(ckpt), page_size=4,
                                     dtype=jnp.float32)
    assert cfg.num_layers == hf_cfg.num_hidden_layers

    prompt = np.random.default_rng(7).integers(1, 250, 12).tolist()
    with torch.no_grad():
        hf_toks = model.generate(
            torch.tensor([prompt]), max_new_tokens=4, do_sample=False,
            pad_token_id=0)[0, len(prompt):].tolist()
    eng = MiniEngine(
        EngineConfig(model=cfg, num_pages=64, max_pages_per_seq=16,
                     model_name="ckpt", pod_identifier="p"),
        params=params)
    assert eng.generate("r", prompt, max_new_tokens=4) == hf_toks


def test_served_tokens_match_hf_greedy():
    """End-to-end: the serving engine over converted weights generates the
    same greedy continuation as transformers' generate()."""
    from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine

    hf_cfg, model = _build_hf(seed=3)
    cfg = config_from_hf(hf_cfg, page_size=4, dtype=jnp.float32)
    params = params_from_hf(model.state_dict(), cfg)

    rng = np.random.default_rng(2)
    prompt = rng.integers(1, 250, 12).tolist()
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor([prompt]), max_new_tokens=6, do_sample=False,
            pad_token_id=0)
    hf_tokens = hf_out[0, len(prompt):].tolist()

    eng = MiniEngine(
        EngineConfig(model=cfg, num_pages=64, max_pages_per_seq=16,
                     model_name="hf", pod_identifier="p"),
        params=params)
    ours = eng.generate("r", prompt, max_new_tokens=6)
    assert ours == hf_tokens, (ours, hf_tokens)
