"""Hybrid engine + shared-storage offload: both cache groups write
through (group 1 = in-window blocks only), and restore is all-or-nothing
on the SWA trailing window — a resume needs group 0's full chain plus
exactly the window, never partial SWA state.
"""

import glob
import os

import numpy as np
import pytest

from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec

from tests.test_hybrid_engine import PAGE, WINDOW, hybrid_cfg

PROMPT = list(range(1, 21))  # 5 blocks; window = 2 blocks


def make_spec(tmp_path, **kw):
    cfg = hybrid_cfg()
    base = dict(
        root=str(tmp_path), model_name="tiny-hybrid", page_size=PAGE,
        num_layers=cfg.num_layers, kv_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim, io_threads=2,
        sliding_window=cfg.sliding_window, swa_layers=cfg.swa_layers,
    )
    base.update(kw)
    return SharedStorageOffloadSpec(**base)


def make_engine(tmp_path=None, **kw):
    return MiniEngine(
        EngineConfig(
            model=hybrid_cfg(), num_pages=64, max_pages_per_seq=16,
            model_name="tiny-hybrid", pod_identifier="pod-h",
        ),
        offload_spec=make_spec(tmp_path) if tmp_path is not None else None,
        **kw,
    )


def group_files(root, group):
    return glob.glob(os.path.join(str(root), "**", f"*_g{group}", "*.bin"),
                     recursive=True)


class TestHybridWriteThrough:
    def test_both_groups_store(self, tmp_path):
        eng = make_engine(tmp_path)
        eng.generate("a", PROMPT, max_new_tokens=2)
        eng.flush_offload()
        g0 = group_files(tmp_path, 0)
        g1 = group_files(tmp_path, 1)
        # group 0: every full prompt block; group 1: only the trailing
        # window (2 of 5 blocks)
        assert len(g0) == 5
        assert len(g1) == WINDOW // PAGE
        eng.offload_handlers.shutdown()


class TestHybridRestore:
    def test_restore_matches_cold_run(self, tmp_path):
        warm = make_engine(tmp_path)
        out_cold = warm.generate("a", PROMPT, max_new_tokens=4)
        warm.flush_offload()
        warm.offload_handlers.shutdown()

        resumed = make_engine(tmp_path)
        req = resumed.add_request("b", PROMPT, max_new_tokens=4)
        # full-chain restore: group 0 chain + group 1 trailing window
        assert req.cached_len == len(PROMPT) // PAGE * PAGE
        while not req.done:
            resumed.step()
        # same model weights (same seed) must produce the same output
        assert req.output == out_cold
        resumed.offload_handlers.shutdown()

    def test_missing_swa_window_skips_restore(self, tmp_path):
        warm = make_engine(tmp_path)
        out_cold = warm.generate("a", PROMPT, max_new_tokens=4)
        warm.flush_offload()
        warm.offload_handlers.shutdown()
        for f in group_files(tmp_path, 1):
            os.unlink(f)

        resumed = make_engine(tmp_path)
        req = resumed.add_request("b", PROMPT, max_new_tokens=4)
        # window unavailable -> conservative: no restore, full recompute
        assert req.cached_len == 0
        while not req.done:
            resumed.step()
        assert req.output == out_cold  # correctness unaffected
        resumed.offload_handlers.shutdown()

    def test_offload_run_matches_plain_hybrid(self, tmp_path):
        plain = make_engine()
        out_plain = plain.generate("a", PROMPT, max_new_tokens=4)
        offl = make_engine(tmp_path)
        out_offl = offl.generate("a", PROMPT, max_new_tokens=4)
        assert out_offl == out_plain
        offl.flush_offload()
        offl.offload_handlers.shutdown()


class TestHybridOffloadGuards:
    def test_window_change_changes_fingerprint(self, tmp_path):
        """KV written under one window must never be resumed by a redeploy
        with a different window — the fingerprint must diverge."""
        fp8 = make_spec(tmp_path).build_mapper().fingerprint
        fp16 = make_spec(tmp_path, sliding_window=16).build_mapper().fingerprint
        fp_split = make_spec(tmp_path, swa_layers=(0,)).build_mapper().fingerprint
        assert len({fp8, fp16, fp_split}) == 3

    def test_object_backend_hybrid_restore(self, tmp_path):
        """The object-store backend routes per-group copiers too: a hybrid
        engine writes both groups and a fresh pod resumes from the store."""
        spec = make_spec(tmp_path, backend="object", parallel_agnostic=True)
        warm = MiniEngine(
            EngineConfig(
                model=hybrid_cfg(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny-hybrid", pod_identifier="pod-h",
            ),
            offload_spec=spec,
        )
        out_cold = warm.generate("a", PROMPT, max_new_tokens=4)
        warm.flush_offload()
        warm.offload_handlers.shutdown()

        resumed = MiniEngine(
            EngineConfig(
                model=hybrid_cfg(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny-hybrid", pod_identifier="pod-i",
            ),
            offload_spec=make_spec(tmp_path, backend="object",
                                   parallel_agnostic=True),
        )
        req = resumed.add_request("b", PROMPT, max_new_tokens=4)
        assert req.cached_len == len(PROMPT) // PAGE * PAGE
        while not req.done:
            resumed.step()
        assert req.output == out_cold
        resumed.offload_handlers.shutdown()
