"""Unit tests for the resilience primitives (docs/resilience.md).

Each module gets a focused suite with injected clocks/RNGs — no sleeps,
no sockets: failpoint registry semantics (env spec grammar, times /
probability budgets, determinism), retry/backoff math and exhaustion,
circuit-breaker state transitions, the CRC32 offload footer, pod
liveness decay, and the FailoverIndex primary/fallback contract.
The live end-to-end chaos paths are in test_failure_recovery.py.
"""

import random

import pytest

from llmd_kv_cache_tpu.core.keys import TIER_TPU_HBM, PodEntry
from llmd_kv_cache_tpu.index import InMemoryIndex, InMemoryIndexConfig
from llmd_kv_cache_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    FailoverIndex,
    FaultInjected,
    IntegrityError,
    PodLivenessTracker,
    RetryExhausted,
    RetryPolicy,
    build_footer,
    call_with_retry,
    failpoints,
    footer_size,
    parse_footer,
    slot_crcs,
)
from llmd_kv_cache_tpu.resilience.failpoints import FailpointRegistry
from llmd_kv_cache_tpu.resilience.integrity import verify_slots


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset(seed=1337)
    yield
    failpoints.reset()


class TestFailpointRegistry:
    def test_disarmed_is_noop(self):
        failpoints.hit("nope.never.armed")  # must not raise
        assert not failpoints.should_fire("nope.never.armed")
        assert failpoints.stats("nope.never.armed") == (0, 0)

    def test_error_mode_raises_with_name(self):
        failpoints.arm("x.y", mode="error")
        with pytest.raises(FaultInjected) as ei:
            failpoints.hit("x.y")
        assert ei.value.failpoint == "x.y"

    def test_times_budget(self):
        failpoints.arm("x.y", times=2)
        for _ in range(2):
            with pytest.raises(FaultInjected):
                failpoints.hit("x.y")
        failpoints.hit("x.y")  # budget spent: no-op
        hits, fired = failpoints.stats("x.y")
        assert (hits, fired) == (3, 2)

    def test_probability_is_seed_deterministic(self):
        def run(seed):
            reg = FailpointRegistry(seed=seed)
            reg.arm("p", probability=0.5)
            return [reg.should_fire("p") for _ in range(32)]

        seq = run(7)
        assert run(7) == seq  # same seed replays exactly
        assert any(seq) and not all(seq)

    def test_custom_mode_should_fire(self):
        failpoints.arm("c", mode="custom", times=1)
        assert failpoints.should_fire("c")
        assert not failpoints.should_fire("c")

    def test_env_spec_grammar(self):
        reg = FailpointRegistry()
        reg.configure_from_env({
            "KVTPU_FAILPOINTS":
                "a.b=error:times=2, c.d=custom:p=0.5 ,e.f=delay:delay=0.01",
            "KVTPU_FAILPOINT_SEED": "99",
        })
        for name in ("a.b", "c.d", "e.f"):
            assert reg.is_armed(name)
        with pytest.raises(FaultInjected):
            reg.hit("a.b")

    def test_bad_spec_rejected(self):
        reg = FailpointRegistry()
        with pytest.raises(ValueError):
            reg._arm_from_spec("a.b=error:bogus=1")
        with pytest.raises(ValueError):
            reg.arm("x", mode="explode")
        with pytest.raises(ValueError):
            reg.arm("x", probability=1.5)

    def test_reset_disarms(self):
        failpoints.arm("x.y")
        failpoints.reset()
        failpoints.hit("x.y")  # no-op again


class TestRetryPolicy:
    def test_delay_grows_and_caps(self):
        p = RetryPolicy(base_delay_s=0.1, max_delay_s=0.5, multiplier=2.0,
                        jitter=False)
        assert [p.delay(n) for n in range(4)] == [0.1, 0.2, 0.4, 0.5]

    def test_jitter_stays_under_cap(self):
        p = RetryPolicy(base_delay_s=0.1, max_delay_s=0.5, jitter=True)
        rng = random.Random(3)
        for n in range(6):
            assert 0.0 <= p.delay(n, rng) <= 0.5

    def test_retry_until_success(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("flaky")
            return "ok"

        out = call_with_retry(fn, RetryPolicy(max_attempts=5, jitter=False),
                              sleep=lambda s: None)
        assert out == "ok" and len(calls) == 3

    def test_exhaustion_chains_last_error(self):
        def fn():
            raise OSError("down")

        with pytest.raises(RetryExhausted) as ei:
            call_with_retry(fn, RetryPolicy(max_attempts=2, jitter=False),
                            sleep=lambda s: None)
        assert isinstance(ei.value.__cause__, OSError)

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            call_with_retry(
                fn, RetryPolicy(max_attempts=5),
                retryable=lambda e: isinstance(e, OSError),
                sleep=lambda s: None,
            )
        assert len(calls) == 1  # no second attempt for a non-transient error

    def test_deadline_stops_early(self):
        now = [0.0]

        def fn():
            raise OSError("slow outage")

        with pytest.raises(RetryExhausted):
            call_with_retry(
                fn,
                RetryPolicy(max_attempts=50, base_delay_s=1.0, jitter=False,
                            deadline_s=2.5),
                clock=lambda: now[0],
                sleep=lambda s: now.__setitem__(0, now[0] + s),
            )
        assert now[0] <= 2.5  # gave up at the deadline, not after 50 tries


class TestCircuitBreaker:
    def _breaker(self, clock):
        return CircuitBreaker(target="t", failure_threshold=3,
                              reset_timeout_s=10.0, clock=clock)

    def test_opens_after_threshold_then_recovers(self):
        now = [0.0]
        b = self._breaker(lambda: now[0])
        assert b.state == "closed"
        for _ in range(3):
            b.record_failure()
        assert b.state == "open"
        assert not b.allow()

        now[0] = 10.0  # reset timeout elapsed: one probe allowed
        assert b.state == "half_open"
        assert b.allow()
        assert not b.allow()  # probe slot already claimed
        b.record_success()
        assert b.state == "closed"

    def test_half_open_failure_reopens(self):
        now = [0.0]
        b = self._breaker(lambda: now[0])
        for _ in range(3):
            b.record_failure()
        now[0] = 10.0
        assert b.allow()
        b.record_failure()  # probe failed
        assert b.state == "open"
        assert not b.allow()

    def test_call_raises_circuit_open_with_retry_after(self):
        now = [0.0]
        b = self._breaker(lambda: now[0])
        for _ in range(3):
            with pytest.raises(OSError):
                b.call(lambda: (_ for _ in ()).throw(OSError("x")))
        now[0] = 4.0
        with pytest.raises(CircuitOpenError) as ei:
            b.call(lambda: "unreachable")
        assert ei.value.retry_after_s == pytest.approx(6.0)

    def test_success_resets_failure_streak(self):
        b = self._breaker(lambda: 0.0)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"  # streak restarted, threshold not met


class TestIntegrityFooter:
    def test_roundtrip(self):
        bufs = [b"hello", b"world!", bytes(range(64))]
        footer = build_footer(slot_crcs(bufs))
        assert len(footer) == footer_size(len(bufs))
        assert parse_footer(footer, len(bufs)) == slot_crcs(bufs)
        verify_slots(bufs, footer)  # no raise

    def test_bit_flip_detected(self):
        bufs = [bytearray(b"payload-a"), bytearray(b"payload-b")]
        footer = build_footer(slot_crcs(bufs))
        bufs[1][3] ^= 0x01
        with pytest.raises(IntegrityError, match="slot 1"):
            verify_slots(bufs, footer)

    def test_bad_magic(self):
        footer = bytearray(build_footer(slot_crcs([b"x"])))
        footer[-8:-4] = b"XXXX"
        with pytest.raises(IntegrityError, match="magic"):
            parse_footer(bytes(footer), 1)

    def test_wrong_slot_count_and_truncation(self):
        footer = build_footer(slot_crcs([b"a", b"b"]))
        with pytest.raises(IntegrityError):
            parse_footer(footer, 3)  # length mismatch
        with pytest.raises(IntegrityError):
            parse_footer(footer[:-1], 2)  # truncated tail


class TestPodLivenessTracker:
    def _tracker(self, clock):
        return PodLivenessTracker(stale_after_s=10.0, drop_after_s=30.0,
                                  clock=lambda: clock[0])

    def test_decay_curve(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("p")
        assert t.factor("p") == 1.0
        clock[0] = 10.0
        assert t.factor("p") == 1.0  # exactly at the stale edge
        clock[0] = 20.0
        assert t.factor("p") == pytest.approx(0.5)
        clock[0] = 30.0
        assert t.factor("p") == 0.0

    def test_unknown_pod_scores_full(self):
        t = self._tracker([0.0])
        assert t.factor("never-seen") == 1.0
        assert t.last_seen("never-seen") is None
        assert t.staleness("never-seen") is None

    def test_mark_removed_forgets(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("p")
        t.mark_removed("p")
        clock[0] = 100.0
        assert t.factor("p") == 1.0  # unknown again, not dead

    def test_snapshot(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("a")
        clock[0] = 20.0
        t.touch("b")
        snap = t.snapshot()
        assert snap["b"] == 1.0 and snap["a"] == pytest.approx(0.5)

    def test_invalid_thresholds_rejected(self):
        with pytest.raises(ValueError):
            PodLivenessTracker(stale_after_s=30.0, drop_after_s=30.0)


class _FlakyIndex:
    """Index test double whose every op raises until healed."""

    def __init__(self):
        self.down = False
        self.store = InMemoryIndex(InMemoryIndexConfig())

    def _guard(self):
        if self.down:
            raise ConnectionError("primary down")

    def lookup(self, request_keys, pod_identifier_set=None):
        self._guard()
        return self.store.lookup(request_keys, pod_identifier_set)

    def add(self, engine_keys, request_keys, entries):
        self._guard()
        self.store.add(engine_keys, request_keys, entries)

    def evict(self, key, key_type, entries):
        self._guard()
        self.store.evict(key, key_type, entries)

    def get_request_key(self, engine_key):
        self._guard()
        return self.store.get_request_key(engine_key)

    def clear(self, pod_identifier):
        self._guard()
        self.store.clear(pod_identifier)


class TestFailoverIndex:
    def _make(self, clock):
        primary = _FlakyIndex()
        idx = FailoverIndex(
            primary,
            InMemoryIndex(InMemoryIndexConfig()),
            retry_policy=RetryPolicy(max_attempts=1, base_delay_s=0.001),
            breaker=CircuitBreaker(target="t", failure_threshold=2,
                                   reset_timeout_s=10.0,
                                   clock=lambda: clock[0]),
        )
        return primary, idx

    def test_writes_mirror_to_fallback(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        entry = PodEntry(pod_identifier="pod", device_tier=TIER_TPU_HBM)
        idx.add(None, [1, 2], [entry])
        assert set(idx.fallback.lookup([1, 2])) == {1, 2}
        assert set(primary.store.lookup([1, 2])) == {1, 2}

    def test_reads_fail_over_without_raising(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        entry = PodEntry(pod_identifier="pod", device_tier=TIER_TPU_HBM)
        idx.add(None, [1], [entry])
        primary.down = True
        assert set(idx.lookup([1])) == {1}  # served by the fallback
        assert idx.failovers == 1

    def test_breaker_opens_and_write_is_absorbed(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        entry = PodEntry(pod_identifier="pod", device_tier=TIER_TPU_HBM)
        primary.down = True
        idx.lookup([1])
        idx.lookup([2])
        assert idx.breaker.state == "open"
        idx.add(None, [3], [entry])  # no raise while the breaker is open
        assert set(idx.lookup([3])) == {3}
        assert 3 not in primary.store.lookup([3])  # primary missed the write

    def test_probe_recloses_after_heal(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        primary.down = True
        idx.lookup([1])
        idx.lookup([1])
        assert idx.breaker.state == "open"
        primary.down = False
        clock[0] = 10.0  # reset timeout elapsed: probe admitted
        idx.lookup([1])
        assert idx.breaker.state == "closed"
