"""Unit tests for the resilience primitives (docs/resilience.md).

Each module gets a focused suite with injected clocks/RNGs — no sleeps,
no sockets: failpoint registry semantics (env spec grammar, times /
probability budgets, determinism), retry/backoff math and exhaustion,
circuit-breaker state transitions, the CRC32 offload footer, pod
liveness decay, and the FailoverIndex primary/fallback contract.
The live end-to-end chaos paths are in test_failure_recovery.py.
"""

import random

import pytest

from llmd_kv_cache_tpu.core.keys import TIER_TPU_HBM, PodEntry
from llmd_kv_cache_tpu.index import InMemoryIndex, InMemoryIndexConfig
from llmd_kv_cache_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    FailoverIndex,
    FaultInjected,
    IntegrityError,
    PodLivenessTracker,
    RetryExhausted,
    RetryPolicy,
    build_footer,
    call_with_retry,
    failpoints,
    footer_size,
    parse_footer,
    slot_crcs,
)
from llmd_kv_cache_tpu.resilience.failpoints import FailpointRegistry
from llmd_kv_cache_tpu.resilience.integrity import verify_slots


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset(seed=1337)
    yield
    failpoints.reset()


class TestFailpointRegistry:
    def test_disarmed_is_noop(self):
        failpoints.hit("nope.never.armed")  # must not raise
        assert not failpoints.should_fire("nope.never.armed")
        assert failpoints.stats("nope.never.armed") == (0, 0)

    def test_error_mode_raises_with_name(self):
        failpoints.arm("x.y", mode="error")
        with pytest.raises(FaultInjected) as ei:
            failpoints.hit("x.y")
        assert ei.value.failpoint == "x.y"

    def test_times_budget(self):
        failpoints.arm("x.y", times=2)
        for _ in range(2):
            with pytest.raises(FaultInjected):
                failpoints.hit("x.y")
        failpoints.hit("x.y")  # budget spent: no-op
        hits, fired = failpoints.stats("x.y")
        assert (hits, fired) == (3, 2)

    def test_probability_is_seed_deterministic(self):
        def run(seed):
            reg = FailpointRegistry(seed=seed)
            reg.arm("p", probability=0.5)
            return [reg.should_fire("p") for _ in range(32)]

        seq = run(7)
        assert run(7) == seq  # same seed replays exactly
        assert any(seq) and not all(seq)

    def test_custom_mode_should_fire(self):
        failpoints.arm("c", mode="custom", times=1)
        assert failpoints.should_fire("c")
        assert not failpoints.should_fire("c")

    def test_env_spec_grammar(self):
        reg = FailpointRegistry()
        reg.configure_from_env({
            "KVTPU_FAILPOINTS":
                "a.b=error:times=2, c.d=custom:p=0.5 ,e.f=delay:delay=0.01",
            "KVTPU_FAILPOINT_SEED": "99",
        })
        for name in ("a.b", "c.d", "e.f"):
            assert reg.is_armed(name)
        with pytest.raises(FaultInjected):
            reg.hit("a.b")

    def test_bad_spec_rejected(self):
        reg = FailpointRegistry()
        with pytest.raises(ValueError):
            reg._arm_from_spec("a.b=error:bogus=1")
        with pytest.raises(ValueError):
            reg.arm("x", mode="explode")
        with pytest.raises(ValueError):
            reg.arm("x", probability=1.5)

    def test_reset_disarms(self):
        failpoints.arm("x.y")
        failpoints.reset()
        failpoints.hit("x.y")  # no-op again


class TestRetryPolicy:
    def test_delay_grows_and_caps(self):
        p = RetryPolicy(base_delay_s=0.1, max_delay_s=0.5, multiplier=2.0,
                        jitter=False)
        assert [p.delay(n) for n in range(4)] == [0.1, 0.2, 0.4, 0.5]

    def test_jitter_stays_under_cap(self):
        p = RetryPolicy(base_delay_s=0.1, max_delay_s=0.5, jitter=True)
        rng = random.Random(3)
        for n in range(6):
            assert 0.0 <= p.delay(n, rng) <= 0.5

    def test_retry_until_success(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("flaky")
            return "ok"

        out = call_with_retry(fn, RetryPolicy(max_attempts=5, jitter=False),
                              sleep=lambda s: None)
        assert out == "ok" and len(calls) == 3

    def test_exhaustion_chains_last_error(self):
        def fn():
            raise OSError("down")

        with pytest.raises(RetryExhausted) as ei:
            call_with_retry(fn, RetryPolicy(max_attempts=2, jitter=False),
                            sleep=lambda s: None)
        assert isinstance(ei.value.__cause__, OSError)

    def test_non_retryable_propagates_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            call_with_retry(
                fn, RetryPolicy(max_attempts=5),
                retryable=lambda e: isinstance(e, OSError),
                sleep=lambda s: None,
            )
        assert len(calls) == 1  # no second attempt for a non-transient error

    def test_deadline_stops_early(self):
        now = [0.0]

        def fn():
            raise OSError("slow outage")

        with pytest.raises(RetryExhausted):
            call_with_retry(
                fn,
                RetryPolicy(max_attempts=50, base_delay_s=1.0, jitter=False,
                            deadline_s=2.5),
                clock=lambda: now[0],
                sleep=lambda s: now.__setitem__(0, now[0] + s),
            )
        assert now[0] <= 2.5  # gave up at the deadline, not after 50 tries


class TestCircuitBreaker:
    def _breaker(self, clock):
        return CircuitBreaker(target="t", failure_threshold=3,
                              reset_timeout_s=10.0, clock=clock)

    def test_opens_after_threshold_then_recovers(self):
        now = [0.0]
        b = self._breaker(lambda: now[0])
        assert b.state == "closed"
        for _ in range(3):
            b.record_failure()
        assert b.state == "open"
        assert not b.allow()

        now[0] = 10.0  # reset timeout elapsed: one probe allowed
        assert b.state == "half_open"
        assert b.allow()
        assert not b.allow()  # probe slot already claimed
        b.record_success()
        assert b.state == "closed"

    def test_half_open_failure_reopens(self):
        now = [0.0]
        b = self._breaker(lambda: now[0])
        for _ in range(3):
            b.record_failure()
        now[0] = 10.0
        assert b.allow()
        b.record_failure()  # probe failed
        assert b.state == "open"
        assert not b.allow()

    def test_call_raises_circuit_open_with_retry_after(self):
        now = [0.0]
        b = self._breaker(lambda: now[0])
        for _ in range(3):
            with pytest.raises(OSError):
                b.call(lambda: (_ for _ in ()).throw(OSError("x")))
        now[0] = 4.0
        with pytest.raises(CircuitOpenError) as ei:
            b.call(lambda: "unreachable")
        assert ei.value.retry_after_s == pytest.approx(6.0)

    def test_success_resets_failure_streak(self):
        b = self._breaker(lambda: 0.0)
        b.record_failure()
        b.record_failure()
        b.record_success()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"  # streak restarted, threshold not met


class TestIntegrityFooter:
    def test_roundtrip(self):
        bufs = [b"hello", b"world!", bytes(range(64))]
        footer = build_footer(slot_crcs(bufs))
        assert len(footer) == footer_size(len(bufs))
        assert parse_footer(footer, len(bufs)) == slot_crcs(bufs)
        verify_slots(bufs, footer)  # no raise

    def test_bit_flip_detected(self):
        bufs = [bytearray(b"payload-a"), bytearray(b"payload-b")]
        footer = build_footer(slot_crcs(bufs))
        bufs[1][3] ^= 0x01
        with pytest.raises(IntegrityError, match="slot 1"):
            verify_slots(bufs, footer)

    def test_bad_magic(self):
        footer = bytearray(build_footer(slot_crcs([b"x"])))
        footer[-8:-4] = b"XXXX"
        with pytest.raises(IntegrityError, match="magic"):
            parse_footer(bytes(footer), 1)

    def test_wrong_slot_count_and_truncation(self):
        footer = build_footer(slot_crcs([b"a", b"b"]))
        with pytest.raises(IntegrityError):
            parse_footer(footer, 3)  # length mismatch
        with pytest.raises(IntegrityError):
            parse_footer(footer[:-1], 2)  # truncated tail


class TestPodLivenessTracker:
    def _tracker(self, clock):
        return PodLivenessTracker(stale_after_s=10.0, drop_after_s=30.0,
                                  clock=lambda: clock[0])

    def test_decay_curve(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("p")
        assert t.factor("p") == 1.0
        clock[0] = 10.0
        assert t.factor("p") == 1.0  # exactly at the stale edge
        clock[0] = 20.0
        assert t.factor("p") == pytest.approx(0.5)
        clock[0] = 30.0
        assert t.factor("p") == 0.0

    def test_unknown_pod_scores_full(self):
        t = self._tracker([0.0])
        assert t.factor("never-seen") == 1.0
        assert t.last_seen("never-seen") is None
        assert t.staleness("never-seen") is None

    def test_mark_removed_forgets(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("p")
        t.mark_removed("p")
        clock[0] = 100.0
        assert t.factor("p") == 1.0  # unknown again, not dead

    def test_snapshot(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("a")
        clock[0] = 20.0
        t.touch("b")
        snap = t.snapshot()
        assert snap["b"] == 1.0 and snap["a"] == pytest.approx(0.5)

    def test_invalid_thresholds_rejected(self):
        with pytest.raises(ValueError):
            PodLivenessTracker(stale_after_s=30.0, drop_after_s=30.0)


class _FlakyIndex:
    """Index test double whose every op raises until healed."""

    def __init__(self):
        self.down = False
        self.store = InMemoryIndex(InMemoryIndexConfig())

    def _guard(self):
        if self.down:
            raise ConnectionError("primary down")

    def lookup(self, request_keys, pod_identifier_set=None):
        self._guard()
        return self.store.lookup(request_keys, pod_identifier_set)

    def add(self, engine_keys, request_keys, entries):
        self._guard()
        self.store.add(engine_keys, request_keys, entries)

    def evict(self, key, key_type, entries):
        self._guard()
        self.store.evict(key, key_type, entries)

    def get_request_key(self, engine_key):
        self._guard()
        return self.store.get_request_key(engine_key)

    def clear(self, pod_identifier):
        self._guard()
        self.store.clear(pod_identifier)


class TestFailoverIndex:
    def _make(self, clock):
        primary = _FlakyIndex()
        idx = FailoverIndex(
            primary,
            InMemoryIndex(InMemoryIndexConfig()),
            retry_policy=RetryPolicy(max_attempts=1, base_delay_s=0.001),
            breaker=CircuitBreaker(target="t", failure_threshold=2,
                                   reset_timeout_s=10.0,
                                   clock=lambda: clock[0]),
        )
        return primary, idx

    def test_writes_mirror_to_fallback(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        entry = PodEntry(pod_identifier="pod", device_tier=TIER_TPU_HBM)
        idx.add(None, [1, 2], [entry])
        assert set(idx.fallback.lookup([1, 2])) == {1, 2}
        assert set(primary.store.lookup([1, 2])) == {1, 2}

    def test_reads_fail_over_without_raising(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        entry = PodEntry(pod_identifier="pod", device_tier=TIER_TPU_HBM)
        idx.add(None, [1], [entry])
        primary.down = True
        assert set(idx.lookup([1])) == {1}  # served by the fallback
        assert idx.failovers == 1

    def test_breaker_opens_and_write_is_absorbed(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        entry = PodEntry(pod_identifier="pod", device_tier=TIER_TPU_HBM)
        primary.down = True
        idx.lookup([1])
        idx.lookup([2])
        assert idx.breaker.state == "open"
        idx.add(None, [3], [entry])  # no raise while the breaker is open
        assert set(idx.lookup([3])) == {3}
        assert 3 not in primary.store.lookup([3])  # primary missed the write

    def test_probe_recloses_after_heal(self):
        clock = [0.0]
        primary, idx = self._make(clock)
        primary.down = True
        idx.lookup([1])
        idx.lookup([1])
        assert idx.breaker.state == "open"
        primary.down = False
        clock[0] = 10.0  # reset timeout elapsed: probe admitted
        idx.lookup([1])
        assert idx.breaker.state == "closed"


class TestDeadline:
    def _dl(self, budget, clock):
        from llmd_kv_cache_tpu.resilience.deadline import Deadline

        return Deadline.after(budget, clock=lambda: clock[0])

    def test_remaining_and_expiry(self):
        clock = [0.0]
        dl = self._dl(1.0, clock)
        assert dl.remaining_s() == pytest.approx(1.0)
        assert not dl.expired()
        clock[0] = 1.5
        assert dl.expired()
        assert dl.remaining_s() == pytest.approx(-0.5)

    def test_wire_round_trip_is_relative(self):
        from llmd_kv_cache_tpu.resilience.deadline import Deadline

        clock = [100.0]
        dl = self._dl(0.25, clock)
        ms = dl.to_wire_ms()
        assert ms == 250
        # The receiving peer's clock is wildly different — the budget
        # re-anchors on it untouched (skew-free by construction).
        peer_clock = [5.0]
        peer = Deadline.from_wire_ms(ms, clock=lambda: peer_clock[0])
        assert peer.remaining_s() == pytest.approx(0.25)

    def test_wire_decode_tolerates_garbage(self):
        from llmd_kv_cache_tpu.resilience.deadline import Deadline

        assert Deadline.from_wire_ms(None) is None
        assert Deadline.from_wire_ms(0) is None
        assert Deadline.from_wire_ms(-5) is None
        assert Deadline.from_wire_ms("nonsense") is None
        assert Deadline.from_wire_ms("40") is not None

    def test_nearly_spent_budget_never_encodes_as_none(self):
        clock = [0.0]
        dl = self._dl(0.0004, clock)  # under 1 ms left
        assert dl.to_wire_ms() == 1
        clock[0] = 1.0
        assert dl.to_wire_ms() == 0

    def test_cap_timeout_takes_the_stricter(self):
        clock = [0.0]
        dl = self._dl(0.5, clock)
        assert dl.cap_timeout(2.0) == pytest.approx(0.5)
        assert dl.cap_timeout(0.1) == pytest.approx(0.1)
        assert dl.cap_timeout(None) == pytest.approx(0.5)
        clock[0] = 1.0
        assert dl.cap_timeout(2.0) == 0.0

    def test_check_raises_with_site_and_overrun(self):
        from llmd_kv_cache_tpu.resilience.deadline import DeadlineExceeded

        clock = [0.0]
        dl = self._dl(0.1, clock)
        dl.check("early")  # no raise
        clock[0] = 0.35
        with pytest.raises(DeadlineExceeded) as ei:
            dl.check("scoring.index_lookup")
        assert ei.value.site == "scoring.index_lookup"
        assert ei.value.overrun_s == pytest.approx(0.25)
        assert isinstance(ei.value, TimeoutError)  # legacy handlers catch it

    def test_ambient_scope_keeps_stricter_deadline(self):
        from llmd_kv_cache_tpu.resilience.deadline import (
            current_deadline,
            deadline_scope,
        )

        clock = [0.0]
        outer = self._dl(1.0, clock)
        inner_late = self._dl(5.0, clock)
        inner_early = self._dl(0.2, clock)
        assert current_deadline() is None
        with deadline_scope(outer):
            assert current_deadline() is outer
            with deadline_scope(inner_late):
                assert current_deadline() is outer  # can't extend
            with deadline_scope(inner_early):
                assert current_deadline() is inner_early  # can shrink
            with deadline_scope(None):
                assert current_deadline() is outer  # None never clears outer
        assert current_deadline() is None

    def test_effective_timeout_and_metadata(self):
        from llmd_kv_cache_tpu.resilience.deadline import (
            GRPC_DEADLINE_KEY,
            deadline_metadata,
            deadline_scope,
            effective_timeout,
        )

        clock = [0.0]
        assert effective_timeout(3.0) == 3.0  # no ambient deadline
        assert deadline_metadata() == ()
        with deadline_scope(self._dl(0.5, clock)):
            assert effective_timeout(3.0) == pytest.approx(0.5)
            ((key, value),) = deadline_metadata()
            assert key == GRPC_DEADLINE_KEY
            assert value == "500"

    def test_extract_deadline_from_grpc_metadata(self):
        from llmd_kv_cache_tpu.resilience.deadline import (
            GRPC_DEADLINE_KEY,
            extract_deadline,
        )

        class FakeContext:
            def invocation_metadata(self):
                return ((GRPC_DEADLINE_KEY, "120"), ("traceparent", "x"))

        class BrokenContext:
            def invocation_metadata(self):
                raise RuntimeError("not a real context")

        dl = extract_deadline(FakeContext())
        assert dl is not None and 0.0 < dl.remaining_s() <= 0.12
        assert extract_deadline(None) is None
        assert extract_deadline(BrokenContext()) is None


class TestLatencyQuantileTracker:
    def test_cold_target_returns_none(self):
        from llmd_kv_cache_tpu.resilience import LatencyQuantileTracker

        t = LatencyQuantileTracker(quantile=0.95, min_samples=8)
        assert t.value("shard-0") is None
        for _ in range(7):
            t.observe("shard-0", 0.01)
        assert t.value("shard-0") is None  # still below min_samples
        t.observe("shard-0", 0.01)
        assert t.value("shard-0") is not None

    def test_estimate_sits_in_the_upper_tail(self):
        from llmd_kv_cache_tpu.resilience import LatencyQuantileTracker

        t = LatencyQuantileTracker(quantile=0.9, min_samples=8)
        rng = random.Random(42)
        samples = [rng.uniform(0.001, 0.01) for _ in range(2000)]
        for s in samples:
            t.observe("s", s)
        est = t.value("s")
        below = sum(1 for s in samples if s <= est) / len(samples)
        assert 0.75 <= below <= 1.0  # upper tail, not the median

    def test_targets_are_independent(self):
        from llmd_kv_cache_tpu.resilience import LatencyQuantileTracker

        t = LatencyQuantileTracker(quantile=0.9, min_samples=4)
        for _ in range(16):
            t.observe("fast", 0.001)
            t.observe("slow", 0.1)
        assert t.value("slow") > t.value("fast") * 10
        assert set(t.snapshot()) == {"fast", "slow"}

    def test_invalid_quantile_rejected(self):
        from llmd_kv_cache_tpu.resilience import LatencyQuantileTracker

        with pytest.raises(ValueError):
            LatencyQuantileTracker(quantile=0.3)
        with pytest.raises(ValueError):
            LatencyQuantileTracker(quantile=1.0)


class TestHedgeBudget:
    def test_hedges_capped_at_traffic_fraction(self):
        from llmd_kv_cache_tpu.resilience import HedgeBudget

        b = HedgeBudget(rate=0.1, burst=8.0)
        granted = 0
        for _ in range(200):
            b.on_primary()
            if b.spend():
                granted += 1
        # 200 primaries * 0.1 = 20 tokens earned (+1 initial credit).
        assert granted <= 21
        assert b.hedge_rate() <= 0.15

    def test_burst_bounds_idle_accumulation(self):
        from llmd_kv_cache_tpu.resilience import HedgeBudget

        b = HedgeBudget(rate=1.0, burst=4.0)
        b.on_primary(1000)  # an idle hour of credit
        granted = sum(1 for _ in range(100) if b.spend())
        assert granted == 4  # capped at burst

    def test_denied_accounting(self):
        from llmd_kv_cache_tpu.resilience import HedgeBudget

        b = HedgeBudget(rate=0.0, burst=1.0)
        assert b.spend()  # initial credit
        assert not b.spend()
        stats = b.stats()
        assert stats["hedges"] == 1 and stats["denied"] == 1

    def test_invalid_rate_rejected(self):
        from llmd_kv_cache_tpu.resilience import HedgeBudget

        with pytest.raises(ValueError):
            HedgeBudget(rate=-0.1)


class TestCoDelShedder:
    def _shedder(self, clock, target=0.005, interval=0.1):
        from llmd_kv_cache_tpu.resilience import CoDelShedder

        return CoDelShedder("t", target_delay_s=target, interval_s=interval,
                            clock=lambda: clock[0])

    def test_burst_below_an_interval_never_sheds(self):
        from llmd_kv_cache_tpu.resilience import ADMIT

        clock = [0.0]
        s = self._shedder(clock)
        s.observe_delay(0.05)  # above target...
        clock[0] = 0.05
        s.observe_delay(0.05)  # ...but not yet for a full interval
        assert s.admit() == ADMIT
        assert not s.overloaded

    def test_sustained_delay_browns_out_then_sheds(self):
        from llmd_kv_cache_tpu.resilience import (
            BROWNOUT,
            SHED,
            CoDelShedder,
            PRIORITY_LOW,
        )
        from llmd_kv_cache_tpu.resilience.shedding import (
            PRIORITY_CRITICAL,
            _NORMAL_SHED_AFTER,
        )

        clock = [0.0]
        s = self._shedder(clock)
        s.observe_delay(0.05)
        clock[0] = 0.11  # a full interval above target
        s.observe_delay(0.05)
        assert s.overloaded
        assert s.admit() == BROWNOUT            # normal browns out first
        assert s.admit(PRIORITY_LOW) == SHED    # low sheds immediately
        assert s.admit(PRIORITY_CRITICAL) == "admit"  # critical never sheds
        # Persisting overload ramps the control law until normal sheds too.
        for _ in range(_NORMAL_SHED_AFTER + 2):
            clock[0] += 0.2
            s.observe_delay(0.05)
        assert s.admit() == SHED
        assert s.pressure >= _NORMAL_SHED_AFTER

    def test_recovery_clears_immediately(self):
        from llmd_kv_cache_tpu.resilience import ADMIT

        clock = [0.0]
        s = self._shedder(clock)
        s.observe_delay(0.05)
        clock[0] = 0.11
        s.observe_delay(0.05)
        assert s.overloaded
        s.observe_delay(0.001)  # queue drained
        assert not s.overloaded
        assert s.admit() == ADMIT
        assert s.pressure == 0

    def test_listener_sees_transitions_and_stats_accumulate(self):
        events = []
        clock = [0.0]
        s = self._shedder(clock)
        s.add_listener(lambda event, delay: events.append(event))
        s.observe_delay(0.05)
        clock[0] = 0.11
        s.observe_delay(0.05)
        s.admit()
        s.observe_delay(0.001)
        assert events == ["overload", "clear"]
        stats = s.stats()
        assert stats["site"] == "t"
        assert stats["brownouts"] == 1
        assert 0.0 <= stats["shed_rate"] <= 1.0

    def test_invalid_config_rejected(self):
        from llmd_kv_cache_tpu.resilience import CoDelShedder

        with pytest.raises(ValueError):
            CoDelShedder("t", target_delay_s=0.0)
        with pytest.raises(ValueError):
            CoDelShedder("t", interval_s=-1.0)


class TestFailpointDelayJitter:
    def test_jitter_schedule_is_seed_deterministic(self):
        def schedule(seed):
            reg = FailpointRegistry(seed=seed)
            reg.arm("slow.site", mode="delay", delay_s=0.0, jitter_s=0.004)
            out = []
            for _ in range(6):
                fp = reg._points["slow.site"]
                out.append(fp.rng.uniform(0.0, fp.jitter_s))
            return out

        assert schedule(7) == schedule(7)
        assert schedule(7) != schedule(8)

    def test_jitter_independent_of_probability_stream(self):
        """The per-point jitter RNG must not perturb the registry RNG the
        probability determinism test depends on."""
        reg_plain = FailpointRegistry(seed=11)
        reg_plain.arm("p", probability=0.5)
        plain = [reg_plain.should_fire("p") for _ in range(32)]

        reg_jitter = FailpointRegistry(seed=11)
        reg_jitter.arm("slow", mode="delay", delay_s=0.0, jitter_s=0.01)
        reg_jitter.arm("p", probability=0.5)
        for _ in range(4):
            reg_jitter.hit("slow")  # draws from the per-point RNG only
        assert [reg_jitter.should_fire("p") for _ in range(32)] == plain

    def test_env_spec_grammar_with_jitter(self):
        reg = FailpointRegistry()
        reg.configure_from_env({
            "KVTPU_FAILPOINTS":
                "a.b=delay:delay_ms=20:jitter_ms=5,c.d=delay:delay=0.01:jitter=0.002",
        })
        a = reg._points["a.b"]
        assert a.delay_s == pytest.approx(0.02)
        assert a.jitter_s == pytest.approx(0.005)
        c = reg._points["c.d"]
        assert c.delay_s == pytest.approx(0.01)
        assert c.jitter_s == pytest.approx(0.002)

    def test_negative_jitter_rejected(self):
        reg = FailpointRegistry()
        with pytest.raises(ValueError):
            reg.arm("x", mode="delay", jitter_s=-0.1)


class TestLivenessLatencyDemotion:
    def _tracker(self, clock, demote=0.05, drop=0.5, floor=0.1):
        return PodLivenessTracker(
            stale_after_s=1000.0, drop_after_s=2000.0,
            latency_demote_after_s=demote, latency_drop_after_s=drop,
            latency_floor=floor, clock=lambda: clock[0])

    def test_disabled_by_default(self):
        t = PodLivenessTracker(stale_after_s=10.0, drop_after_s=30.0)
        t.touch("p")
        for _ in range(20):
            t.observe_latency("p", 99.0)
        assert t.factor("p") == 1.0  # latency demotion off unless configured

    def test_needs_min_samples(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("p")
        for _ in range(4):
            t.observe_latency("p", 10.0)
        assert t.latency_factor("p") == 1.0  # not enough evidence yet
        t.observe_latency("p", 10.0)
        assert t.latency_factor("p") < 1.0

    def test_slow_pod_demotes_to_floor_never_zero(self):
        clock = [0.0]
        t = self._tracker(clock, demote=0.05, drop=0.5, floor=0.1)
        t.touch("p")
        for _ in range(50):
            t.observe_latency("p", 10.0)  # EMA converges far past drop
        assert t.latency_factor("p") == pytest.approx(0.1)
        assert t.factor("p") == pytest.approx(0.1)  # slow, not dead

    def test_fast_pod_keeps_full_factor_and_recovers(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("p")
        for _ in range(10):
            t.observe_latency("p", 0.001)
        assert t.latency_factor("p") == 1.0
        for _ in range(10):
            t.observe_latency("p", 0.3)  # mid demotion band
        mid = t.latency_factor("p")
        assert 0.1 < mid < 1.0
        for _ in range(200):
            t.observe_latency("p", 0.001)  # healed: EMA decays back
        assert t.latency_factor("p") == 1.0

    def test_mark_removed_clears_latency_state(self):
        clock = [0.0]
        t = self._tracker(clock)
        t.touch("p")
        for _ in range(10):
            t.observe_latency("p", 10.0)
        t.mark_removed("p")
        assert t.latency_ema("p") is None
        assert t.factor("p") == 1.0

    def test_invalid_latency_config_rejected(self):
        with pytest.raises(ValueError):
            PodLivenessTracker(stale_after_s=10.0, drop_after_s=30.0,
                               latency_demote_after_s=1.0,
                               latency_drop_after_s=0.5)
        with pytest.raises(ValueError):
            PodLivenessTracker(stale_after_s=10.0, drop_after_s=30.0,
                               latency_demote_after_s=1.0,
                               latency_drop_after_s=2.0,
                               latency_floor=1.5)


class TestCircuitBreakerProbeLease:
    """Half-open probe hardening: one concurrent probe, and a lease that
    expires so a dead prober cannot wedge the breaker (runs with the
    lockdep witness armed — the breaker lock is a new_lock())."""

    @pytest.fixture(autouse=True)
    def _witness(self):
        from llmd_kv_cache_tpu.utils import lockdep

        was = lockdep.enabled()
        lockdep.set_enabled(True)
        lockdep.reset()
        yield
        lockdep.set_enabled(was, budget_s=0)
        lockdep.reset()

    def _open_breaker(self, clock, probe_timeout=30.0):
        b = CircuitBreaker(target="t", failure_threshold=1,
                           reset_timeout_s=10.0,
                           probe_timeout_s=probe_timeout,
                           clock=lambda: clock[0])
        b.record_failure()
        assert b.state == "open"
        return b

    def test_single_concurrent_probe(self):
        clock = [0.0]
        b = self._open_breaker(clock)
        clock[0] = 10.0
        assert b.allow()       # probe slot claimed
        assert not b.allow()   # second caller rejected while lease is live
        clock[0] = 20.0        # inside the lease window
        assert not b.allow()

    def test_dead_prober_cannot_wedge_the_breaker(self):
        clock = [0.0]
        b = self._open_breaker(clock, probe_timeout=30.0)
        clock[0] = 10.0
        assert b.allow()  # prober claims the lease... and dies silently
        clock[0] = 39.9
        assert not b.allow()  # lease still live
        clock[0] = 40.0   # lease expired: the breaker makes progress again
        assert b.allow()
        b.record_success()
        assert b.state == "closed"

    def test_stale_probers_report_is_harmless(self):
        clock = [0.0]
        b = self._open_breaker(clock, probe_timeout=30.0)
        clock[0] = 10.0
        assert b.allow()      # prober A (goes quiet)
        clock[0] = 40.0
        assert b.allow()      # prober B reclaims the lease
        b.record_failure()    # A's late failure report
        assert b.state == "open"  # re-opened, not wedged
        clock[0] = 50.0
        assert b.allow()      # and recovery still proceeds
        b.record_success()
        assert b.state == "closed"
