"""Engine decode equivalence: Pallas flash-decode vs XLA reference path."""

from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig


def test_pallas_decode_matches_xla_path():
    prompt = list(range(40, 52))
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(
                model=LlamaConfig.tiny(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny", pod_identifier="p",
                use_pallas_decode=use_pallas,
            ),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=4)
    assert outs[False] == outs[True]
