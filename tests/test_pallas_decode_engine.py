"""Engine decode equivalence: Pallas flash-decode vs XLA reference path."""

from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig


def test_pallas_decode_matches_xla_path():
    prompt = list(range(40, 52))
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(
                model=LlamaConfig.tiny(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny", pod_identifier="p",
                use_pallas_decode=use_pallas,
            ),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=4)
    assert outs[False] == outs[True]


def test_pallas_decode_matches_xla_with_sliding_window():
    """Backend equivalence holds for SWA models too (window masking + page
    skipping in the kernel)."""
    tiny = LlamaConfig.tiny()
    swa = LlamaConfig(
        vocab_size=tiny.vocab_size, hidden_size=tiny.hidden_size,
        num_layers=tiny.num_layers, num_heads=tiny.num_heads,
        num_kv_heads=tiny.num_kv_heads, head_dim=tiny.head_dim,
        intermediate_size=tiny.intermediate_size, page_size=tiny.page_size,
        sliding_window=8, swa_layers=tuple(range(tiny.num_layers)),
    )
    prompt = list(range(60, 84))  # 24-token context >> window 8
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(model=swa, num_pages=64, max_pages_per_seq=16,
                         model_name="swa", pod_identifier="p",
                         use_pallas_decode=use_pallas),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=5)
    assert outs[False] == outs[True]


def test_pallas_prefill_engine_matches_xla_path():
    """With use_pallas_prefill=True the engine prefills through the Pallas
    flash-prefill kernel; outputs must match the XLA path, including
    chunked prefill and prefix-cache resumes. (Prefill defaults to the XLA
    path — measured 12× faster at production shapes — so the kernel is
    opt-in.)"""
    prompt = list(range(30, 62))  # 8 pages of 4
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(
                model=LlamaConfig.tiny(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny", pod_identifier="p",
                use_pallas_decode=use_pallas,
                use_pallas_prefill=use_pallas,
                max_prefill_tokens=16,  # force chunked prefill
            ),
            seed=0,
        )
        first = engine.generate("r", prompt, max_new_tokens=4)
        # resume with a shared prefix: nonzero ctx_lens into the kernel
        resumed = engine.generate("r2", prompt + [7, 8, 9, 10],
                                  max_new_tokens=4)
        outs[use_pallas] = (first, resumed)
    assert outs[False] == outs[True]
