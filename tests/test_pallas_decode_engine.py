"""Engine decode equivalence: Pallas flash-decode vs XLA reference path."""

from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig


def test_pallas_decode_matches_xla_path():
    prompt = list(range(40, 52))
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(
                model=LlamaConfig.tiny(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny", pod_identifier="p",
                use_pallas_decode=use_pallas,
            ),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=4)
    assert outs[False] == outs[True]


def test_pallas_decode_matches_xla_with_sliding_window():
    """Backend equivalence holds for SWA models too (window masking + page
    skipping in the kernel)."""
    tiny = LlamaConfig.tiny()
    swa = LlamaConfig(
        vocab_size=tiny.vocab_size, hidden_size=tiny.hidden_size,
        num_layers=tiny.num_layers, num_heads=tiny.num_heads,
        num_kv_heads=tiny.num_kv_heads, head_dim=tiny.head_dim,
        intermediate_size=tiny.intermediate_size, page_size=tiny.page_size,
        sliding_window=8, swa_layers=tuple(range(tiny.num_layers)),
    )
    prompt = list(range(60, 84))  # 24-token context >> window 8
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(model=swa, num_pages=64, max_pages_per_seq=16,
                         model_name="swa", pod_identifier="p",
                         use_pallas_decode=use_pallas),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=5)
    assert outs[False] == outs[True]


def test_pallas_prefill_engine_matches_xla_path():
    """With use_pallas_prefill=True the engine prefills through the Pallas
    flash-prefill kernel; outputs must match the XLA path, including
    chunked prefill and prefix-cache resumes. (On TPU the flash kernel is
    the auto default — measured 1.9 ms/layer vs XLA's 3.5 at production
    chunks; on CPU auto stays XLA because interpret-mode Pallas is orders
    slower, so this test opts in explicitly.)"""
    prompt = list(range(30, 62))  # 8 pages of 4
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(
                model=LlamaConfig.tiny(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny", pod_identifier="p",
                use_pallas_decode=use_pallas,
                use_pallas_prefill=use_pallas,
                max_prefill_tokens=16,  # force chunked prefill
            ),
            seed=0,
        )
        first = engine.generate("r", prompt, max_new_tokens=4)
        # resume with a shared prefix: nonzero ctx_lens into the kernel
        resumed = engine.generate("r2", prompt + [7, 8, 9, 10],
                                  max_new_tokens=4)
        outs[use_pallas] = (first, resumed)
    assert outs[False] == outs[True]


def test_pallas_decode_matches_xla_with_attention_sinks():
    """Sink models (StreamingLLM, sink_full_attention) decode through the
    flash kernel: the first-S mask applies in-kernel and matches the XLA
    path — the engine no longer gates Pallas off for this family."""
    prompt = list(range(60, 84))  # 24-token context >> window 8, sinks 4
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(model=LlamaConfig.sink_tiny(), num_pages=64,
                         max_pages_per_seq=16, model_name="sink",
                         pod_identifier="p", use_pallas_decode=use_pallas),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=6)
    assert outs[False] == outs[True]


def test_pallas_decode_matches_xla_with_sink_bursts():
    """Fused decode bursts through the kernel for sink models."""
    prompt = list(range(60, 80))
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(model=LlamaConfig.sink_tiny(), num_pages=64,
                         max_pages_per_seq=16, model_name="sink",
                         pod_identifier="p", use_pallas_decode=use_pallas,
                         decode_burst=4),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=8)
    assert outs[False] == outs[True]


def test_pallas_decode_matches_xla_for_mla():
    """Absorbed MLA decodes through the flash kernel as the kv_heads=1
    multi-query case (latent pool passed as both K and V) — the engine no
    longer gates Pallas off for the MLA family."""
    prompt = list(range(40, 64))
    outs = {}
    for use_pallas in (False, True):
        engine = MiniEngine(
            EngineConfig(model=LlamaConfig.deepseek_tiny(), num_pages=64,
                         max_pages_per_seq=16, model_name="ds",
                         pod_identifier="p", use_pallas_decode=use_pallas),
            seed=0,
        )
        outs[use_pallas] = engine.generate("r", prompt, max_new_tokens=6)
    assert outs[False] == outs[True]


def test_mla_latent_pad_is_semantics_invariant():
    """latent_pad (Mosaic lane alignment for the on-chip kernel) must not
    change served tokens: zero key dims score zero and value reads slice
    [:rank], so padded and unpadded engines emit identical streams."""
    base = LlamaConfig.deepseek_tiny()
    padded = LlamaConfig(
        vocab_size=base.vocab_size, hidden_size=base.hidden_size,
        num_layers=base.num_layers, num_heads=base.num_heads,
        num_kv_heads=base.num_kv_heads, head_dim=base.head_dim,
        intermediate_size=base.intermediate_size, page_size=base.page_size,
        kv_lora_rank=base.kv_lora_rank,
        qk_rope_head_dim=base.qk_rope_head_dim,
        latent_pad=104,  # 16+8+104 = 128: the aligned on-chip layout
    )
    prompt = list(range(40, 60))
    outs = {}
    for name, cfg in (("base", base), ("padded", padded)):
        for use_pallas in (False, True):
            engine = MiniEngine(
                EngineConfig(model=cfg, num_pages=64, max_pages_per_seq=16,
                             model_name="ds", pod_identifier="p",
                             use_pallas_decode=use_pallas),
                seed=0,
            )
            outs[name, use_pallas] = engine.generate(
                "r", prompt, max_new_tokens=6)
    assert len({tuple(v) for v in outs.values()}) == 1, outs


def test_pallas_decode_batch_rows_matches_single_row():
    """decode_batch_rows co-schedules batch items per kernel program; the
    served tokens must not change (multi-request batch so the decode
    batch really has multiple rows, with distinct prompts)."""
    prompts = {f"r{i}": list(range(10 + 7 * i, 30 + 7 * i))
               for i in range(4)}
    outs = {}
    for rows in (1, 2, 4):
        engine = MiniEngine(
            EngineConfig(
                model=LlamaConfig.tiny(), num_pages=128,
                max_pages_per_seq=16, model_name="tiny", pod_identifier="p",
                use_pallas_decode=True, decode_batch_rows=rows,
                decode_burst=4,
            ),
            seed=0,
        )
        reqs = {rid: engine.enqueue(rid, p, max_new_tokens=6)
                for rid, p in prompts.items()}
        while not all(r.done for r in reqs.values()):
            engine.step()
        outs[rows] = {rid: list(r.output) for rid, r in reqs.items()}
    assert outs[1] == outs[2] == outs[4]
