"""Hybrid (mixed full/SWA) engine: two cache groups with separate page
pools, group-tagged events, out-of-window reclamation, and the HybridAware
scoring loop fed by a real producer — through ZMQ, with engine block size
different from the indexer's canonical size (many:1 realignment,
reference ``pool.go:227-260`` + ``hma.go:32-66``).
"""

import time

import numpy as np
import pytest

from llmd_kv_cache_tpu.core import GroupCatalog
from llmd_kv_cache_tpu.core.hma import SPEC_FULL_ATTENTION, SPEC_SLIDING_WINDOW
from llmd_kv_cache_tpu.events.model import BlockRemovedEvent, BlockStoredEvent
from llmd_kv_cache_tpu.events.pool import Pool, PoolConfig
from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig
from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig
from llmd_kv_cache_tpu.scoring.scorer import KVBlockScorerConfig

PAGE = 4
WINDOW = 8  # 2 pages


def hybrid_cfg(**kw):
    base = dict(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=PAGE,
        sliding_window=WINDOW, swa_layers=(1,),
    )
    base.update(kw)
    return LlamaConfig(**base)


def make_engine(events=None, num_pages=64, num_swa_pages=None, cfg=None):
    def sink_batch(evs):
        events.extend(evs)

    return MiniEngine(
        EngineConfig(
            model=cfg or hybrid_cfg(),
            num_pages=num_pages,
            num_swa_pages=num_swa_pages,
            max_pages_per_seq=16,
            model_name="tiny-hybrid",
            pod_identifier="pod-h",
            # The shape-aware auto leaves tiny models unfused, which
            # most suites now exercise; this suite pins the FUSED
            # serving layout through the hybrid paging paths so the
            # production hidden>=4096 default keeps integration
            # coverage (r5 review).
            fuse_projections=True,
        ),
        event_sink=sink_batch if events is not None else None,
    )


class TestHybridConfig:
    def test_is_hybrid_detection(self):
        assert hybrid_cfg().is_hybrid
        assert not LlamaConfig.tiny().is_hybrid
        # all-SWA is single-group, not hybrid
        assert not hybrid_cfg(swa_layers=(0, 1)).is_hybrid

    def test_group_layers(self):
        cfg = hybrid_cfg()
        assert cfg.group_layers(0) == (0,)
        assert cfg.group_layers(1) == (1,)
        assert cfg.layer_group(0) == 0
        assert cfg.layer_group(1) == 1


class TestHybridEquivalence:
    def test_hybrid_matches_unified_pool_outputs(self):
        """The two-pool hybrid path must produce the same tokens as the
        same model run through the unified single-pool path (which handles
        per-layer windows in attention but shares one page pool)."""
        cfg = hybrid_cfg()
        prompt = list(np.random.default_rng(0).integers(1, 250, 21))
        hybrid = make_engine(cfg=cfg)
        assert hybrid.hybrid
        out_h = hybrid.generate("r", prompt, max_new_tokens=8)

        # Unified-pool baseline: same weights (same seed), same per-layer
        # windows, one pool — forced by building a non-hybrid engine on a
        # model whose layer_window matches but is_hybrid is False. We get
        # that by running the hybrid config through the single-pool path:
        # construct engine with swa_layers=() then manually compare is not
        # equivalent; instead run forward directly via the unified engine
        # over all layers with windows — covered by the model-level check
        # below. Here: determinism of the hybrid path itself.
        hybrid2 = make_engine(cfg=cfg)
        assert hybrid2.generate("r", prompt, max_new_tokens=8) == out_h

    def test_hybrid_forward_matches_unified_forward(self):
        """Model-level: forward_hybrid over split pools == forward over a
        unified pool, same weights and windows."""
        import jax
        import jax.numpy as jnp

        from llmd_kv_cache_tpu.models.llama import (
            forward, forward_hybrid, init_kv_cache, init_kv_cache_hybrid,
            init_params,
        )

        cfg = hybrid_cfg()
        params = init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(1, 250, (1, 12)), jnp.int32)
        ctx = jnp.zeros((1,), jnp.int32)
        new = jnp.full((1,), 12, jnp.int32)

        k, v = init_kv_cache(cfg, 16)
        table = jnp.asarray(np.arange(1, 9)[None, :], jnp.int32)
        logits_u, _, _ = forward(params, cfg, tokens, k, v, table, ctx, new)

        k0, v0, k1, v1 = init_kv_cache_hybrid(cfg, 16, 16)
        t0 = jnp.asarray(np.arange(1, 9)[None, :], jnp.int32)
        t1 = jnp.asarray(np.arange(1, 9)[None, :], jnp.int32)
        logits_h, *_ = forward_hybrid(
            params, cfg, tokens, k0, v0, k1, v1, t0, t1, ctx, new)
        np.testing.assert_allclose(
            np.asarray(logits_u), np.asarray(logits_h), rtol=2e-2, atol=2e-2)

    def test_prefix_reuse_across_requests(self):
        eng = make_engine()
        prompt = list(range(1, 17))  # 4 full pages
        eng.generate("a", prompt, max_new_tokens=2)
        req = eng.add_request("b", prompt + [99, 98], max_new_tokens=2)
        # After a's finish, group 1 dropped its out-of-window blocks but
        # kept the trailing window; group 0 kept everything. Trailing-
        # window acquisition therefore still yields the FULL prefix hit:
        # resume at 16 needs group 0's chain [0,4) plus group 1's last 2
        # blocks only.
        assert req.cached_len == 16
        # pre-window SWA slots are garbage-mapped, in-window ones real
        assert req.swa_acquired_from == 2
        assert req.swa_pages[:2] == [0, 0] and all(req.swa_pages[2:4])


class TestHybridBurstDecode:
    """Fused decode bursts on the two-pool layout (freeze-and-reclaim SWA
    paging): burst ≥ 8 must be token-identical to single-token stepping."""

    def _serve(self, burst, prompt, n_tokens=12, num_swa_pages=None):
        eng = MiniEngine(
            EngineConfig(
                model=hybrid_cfg(), num_pages=64,
                num_swa_pages=num_swa_pages, max_pages_per_seq=16,
                model_name="tiny-hybrid", pod_identifier="pod-h",
                decode_burst=burst,
            ))
        return eng.generate("r", prompt, max_new_tokens=n_tokens), eng

    def test_burst8_token_identical_to_single_step(self):
        prompt = list(range(10, 29))  # crosses page and window boundaries
        single, _ = self._serve(1, prompt)
        burst, _ = self._serve(8, prompt)
        assert burst == single

    def test_burst16_long_generation_slides_window(self):
        # Generation far beyond the window: burst boundaries land mid-page
        # and mid-window; reclaim happens between bursts only.
        prompt = list(range(30, 37))
        single, _ = self._serve(1, prompt, n_tokens=33)
        burst, _ = self._serve(16, prompt, n_tokens=33)
        assert burst == single

    def test_burst_reclaims_out_of_window_pages(self):
        # After a long burst generation the SWA pool must have recovered
        # the slid-out pages: next request still gets served.
        prompt = list(range(40, 48))
        _, eng = self._serve(8, prompt, n_tokens=24, num_swa_pages=8)
        out2 = eng.generate("r2", list(range(60, 68)), max_new_tokens=24)
        assert len(out2) == 24

    def test_undersized_swa_pool_degrades_to_single_step(self):
        """A pool sized to the single-step bound must not die under
        decode_burst: the step falls back to single-token decoding and
        output stays identical."""
        prompt = list(range(40, 48))
        single, _ = self._serve(1, prompt, n_tokens=16, num_swa_pages=4)
        burst, _ = self._serve(16, prompt, n_tokens=16, num_swa_pages=4)
        assert burst == single

    def test_pallas_burst_matches_xla_burst(self):
        """The flash-decode kernel applies inside hybrid bursts (per layer,
        each layer sees its own group's table/window): interpret-mode
        Pallas bursts are token-identical to the XLA burst path."""
        prompt = list(range(10, 29))
        outs = {}
        for use_pallas in (False, True):
            eng = MiniEngine(
                EngineConfig(
                    model=hybrid_cfg(), num_pages=64, max_pages_per_seq=16,
                    model_name="tiny-hybrid", pod_identifier="pod-h",
                    decode_burst=8, use_pallas_decode=use_pallas,
                ))
            outs[use_pallas] = eng.generate("r", prompt, max_new_tokens=12)
        assert outs[False] == outs[True]

    def test_mixed_batch_budgets(self):
        # Continuous batching: two requests with different budgets decode
        # in one fused burst; each stops at its own max_new_tokens.
        eng = MiniEngine(
            EngineConfig(
                model=hybrid_cfg(), num_pages=64, max_pages_per_seq=16,
                model_name="tiny-hybrid", pod_identifier="pod-h",
                decode_burst=8,
            ))
        a = eng.add_request("a", list(range(10, 18)), max_new_tokens=13)
        b = eng.add_request("b", list(range(20, 28)), max_new_tokens=5)
        for _ in range(40):
            if a.done and b.done:
                break
            eng.step()
        assert len(a.output) == 13 and len(b.output) == 5
        # Token equality vs single-step serving of the same prompts.
        sa, _ = self._serve(1, list(range(10, 18)), n_tokens=13)
        sb, _ = self._serve(1, list(range(20, 28)), n_tokens=5)
        assert a.output == sa and b.output == sb


class TestGroupEvents:
    def test_stored_events_carry_group_specs(self):
        events = []
        eng = make_engine(events)
        eng.generate("a", list(range(1, 17)), max_new_tokens=2)
        stored = [e for e in events if isinstance(e, BlockStoredEvent)]
        by_group = {}
        for e in stored:
            by_group.setdefault(e.group_idx, []).append(e)
        assert set(by_group) == {0, 1}
        assert all(e.kv_cache_spec_kind == SPEC_FULL_ATTENTION
                   for e in by_group[0])
        assert all(e.kv_cache_spec_kind == SPEC_SLIDING_WINDOW
                   and e.kv_cache_spec_sliding_window == WINDOW
                   for e in by_group[1])
        # Group 1 stores only the in-window trailing suffix of the chain:
        # out-of-window blocks are reclaimed pre-commit and never
        # advertised (prompt 16 tokens, window 8 → last 2 of 4 blocks).
        g0 = [h for e in by_group[0] for h in e.block_hashes]
        g1 = [h for e in by_group[1] for h in e.block_hashes]
        assert len(g0) == 4 and g1 == g0[2:]

    def test_prompt_tail_swa_window_survives_decode(self):
        """Decode sliding the live window past the prompt tail must NOT
        revoke committed SWA blocks: block i always serves a resume at
        boundary i+1 (whose trailing window covers it), so committed SWA
        blocks stay cached like full-attention blocks and only pressure
        eviction (or clear) revokes them. An earlier policy dropped them
        eagerly against the FINAL context's window, which destroyed
        exactly the blocks a prompt replay resumes from."""
        events = []
        eng = make_engine(events)
        prompt = list(range(1, 17))  # 4 blocks; window = 2 blocks
        eng.generate("a", prompt, max_new_tokens=10)  # context grows to 26
        stored1 = [h for e in events
                   if isinstance(e, BlockStoredEvent) and e.group_idx == 1
                   for h in e.block_hashes]
        assert stored1  # blocks 2,3 were in-window at commit
        assert not any(isinstance(e, BlockRemovedEvent) and e.group_idx == 1
                       for e in events)
        # And they really do serve a replay: full prompt-prefix hit,
        # token-identical continuation.
        req2 = eng.add_request("replay", prompt, max_new_tokens=1)
        assert req2.cached_len == len(prompt)
        # Deeper prompts resume straight through them too.
        req3 = eng.add_request("deeper", prompt + list(range(101, 109)),
                               max_new_tokens=1)
        assert req3.cached_len >= len(prompt)
        assert not any(isinstance(e, BlockRemovedEvent) and e.group_idx == 0
                       for e in events)

    def test_swa_pool_reuse_after_drop(self):
        """Dropped SWA pages return to the pool: a small SWA pool survives
        many sequential requests."""
        eng = make_engine(num_swa_pages=20)
        for i in range(4):
            prompt = list(np.random.default_rng(i).integers(1, 250, 17))
            eng.generate(f"r{i}", prompt, max_new_tokens=2)
        assert eng.swa_manager.num_free() > 0

    def test_window_bounded_swa_pool_fits_long_prompt(self):
        """The documented memory win: with just-in-time allocation and
        mid-prefill reclamation, a prompt much longer than the SWA pool
        fits — demand is window + chunk, not prompt length."""
        eng = MiniEngine(EngineConfig(
            model=hybrid_cfg(),
            num_pages=64,
            num_swa_pages=10,        # 40-token prompt needs 10 blocks alone
            max_pages_per_seq=16,
            max_prefill_tokens=8,    # 2-page chunks
            model_name="tiny-hybrid",
            pod_identifier="pod-h",
        ))
        prompt = list(np.random.default_rng(7).integers(1, 250, 40))
        out = eng.generate("long", prompt, max_new_tokens=4)
        assert len(out) == 4
        # steady state: only in-window slots hold pages
        assert eng.swa_manager.num_free() >= 10 - 1 - (WINDOW // PAGE + 2)

    def test_window_bounded_pool_matches_unbounded_outputs(self):
        """Reclaiming out-of-window SWA pages must not change results."""
        prompt = list(np.random.default_rng(9).integers(1, 250, 33))

        def run(num_swa_pages, max_prefill):
            eng = MiniEngine(EngineConfig(
                model=hybrid_cfg(), num_pages=64,
                num_swa_pages=num_swa_pages, max_pages_per_seq=16,
                max_prefill_tokens=max_prefill,
                model_name="tiny-hybrid", pod_identifier="pod-h",
            ))
            return eng.generate("r", prompt, max_new_tokens=6)

        assert run(10, 8) == run(64, 512)


class TestHybridScoringE2E:
    def test_zmq_realigned_hybrid_scoring(self, tmp_path):
        """The full loop, from a REAL producer: hybrid engine (block size 4)
        → ZMQ publisher → subscriber → pool (canonical block size 8, many:1
        realignment) → GroupCatalog → HybridAwareScorer."""
        from llmd_kv_cache_tpu.events.publisher import KVEventPublisher
        from llmd_kv_cache_tpu.events.zmq_subscriber import ZMQSubscriber

        endpoint = "ipc://" + str(tmp_path / "events.ipc")

        indexer = Indexer(IndexerConfig.from_dict({
            "tokenProcessorConfig": {"blockSize": 8},  # canonical ≠ engine 4
            "kvBlockScorerConfig": {"scoringStrategy": "HybridAware"},
        }))
        pool = Pool(PoolConfig(concurrency=1), indexer.kv_block_index,
                    indexer.token_processor)
        indexer.attach_group_catalog(pool.group_catalog)
        pool.start()
        sub = ZMQSubscriber(endpoint, "kv@", pool.add_task, bind=True)
        sub.start()
        time.sleep(0.2)

        publisher = KVEventPublisher(endpoint, "pod-h", "tiny-hybrid",
                                     bind=False)
        eng = make_engine()
        eng_events = []
        eng.block_manager.event_sink = lambda evs: (
            eng_events.extend(evs), publisher.publish(evs))
        eng.swa_manager.event_sink = eng.block_manager.event_sink

        try:
            prompt = list(range(1, 33))  # 8 engine blocks = 4 canonical
            eng.generate("warm", prompt, max_new_tokens=2)

            # republish-until-observed: PUB/SUB joins are slow
            deadline = time.monotonic() + 10
            scores = {}
            while time.monotonic() < deadline:
                scores = indexer.score_tokens(prompt, "tiny-hybrid")
                if scores:
                    break
                publisher.publish(
                    [e for e in eng_events if isinstance(e, BlockStoredEvent)])
                time.sleep(0.1)
            assert "pod-h" in scores, "hybrid pod never scored"

            # The catalog learned both groups from the wire.
            cat = pool.group_catalog
            g0 = cat.get("pod-h", 0)
            g1 = cat.get("pod-h", 1)
            assert g0 is not None and g0.kind == SPEC_FULL_ATTENTION
            assert g1 is not None and g1.kind == SPEC_SLIDING_WINDOW
            assert g1.sliding_window_size == WINDOW

            # SWA cap: score is min(full-group value, window value); the
            # window (8 tokens = 1 canonical block) caps the pod's score
            # at the weight of the trailing canonical block.
            assert scores["pod-h"] <= 2.0
        finally:
            publisher.close()
            sub.stop()
            pool.shutdown()
