"""Multi-block offload files with unaligned head/tail spans.

Counterpart of the reference's ``gpu_blocks_per_file > 1`` layout
(``spec.py:76-89``) and its per-file block mapping with head offsets
(``worker.py:187-255``): files hold N consecutive blocks in fixed slots;
transfers may start and end mid-file.
"""

import numpy as np
import pytest

from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec
from llmd_kv_cache_tpu.offload.worker import FileSpan, map_blocks_to_file_spans

from tests.test_offload import make_caches, wait_results


class TestSpanMapping:
    def test_aligned_full_files(self):
        spans = map_blocks_to_file_spans(
            [11, 22], start_block_idx=0,
            blocks=[[0], [1], [2], [3], [4], [5], [6], [7]],
            blocks_per_file=4,
        )
        assert [(s.file_key, s.head_offset, len(s.blocks)) for s in spans] == [
            (11, 0, 4), (22, 0, 4),
        ]
        assert spans[1].blocks == [[4], [5], [6], [7]]

    def test_unaligned_head(self):
        # range [2, 6) over 4-block files: head-partial file 0 (slots 2-3),
        # then head of file 1 (slots 0-1).
        spans = map_blocks_to_file_spans(
            [11, 22], start_block_idx=2,
            blocks=[[2], [3], [4], [5]], blocks_per_file=4,
        )
        assert [(s.file_key, s.head_offset, len(s.blocks)) for s in spans] == [
            (11, 2, 2), (22, 0, 2),
        ]

    def test_unaligned_tail(self):
        spans = map_blocks_to_file_spans(
            [11], start_block_idx=4, blocks=[[0], [1]], blocks_per_file=4,
        )
        assert [(s.file_key, s.head_offset, len(s.blocks)) for s in spans] == [
            (11, 0, 2),
        ]

    def test_mid_file_only(self):
        spans = map_blocks_to_file_spans(
            [11], start_block_idx=5, blocks=[[0], [1]], blocks_per_file=4,
        )
        assert [(s.file_key, s.head_offset, len(s.blocks)) for s in spans] == [
            (11, 1, 2),
        ]

    def test_key_count_mismatch_raises(self):
        with pytest.raises(ValueError, match="spans 2 files"):
            map_blocks_to_file_spans(
                [11], start_block_idx=2, blocks=[[0], [1], [2]],
                blocks_per_file=4,
            )

    def test_empty(self):
        assert map_blocks_to_file_spans([], 0, [], 4) == []


def make_handlers(tmp_path, blocks_per_file=4, seed=0):
    spec = SharedStorageOffloadSpec(
        root=str(tmp_path), model_name="m", page_size=4,
        num_layers=2, kv_heads=2, head_dim=8, io_threads=2,
        blocks_per_file=blocks_per_file, pages_per_block=1,
    )
    k, v = make_caches(seed=seed)
    return spec, spec.get_handlers(k, v)


class TestMultiBlockRoundTrip:
    def test_four_block_file_roundtrip(self, tmp_path):
        spec, handlers = make_handlers(tmp_path)
        try:
            pages = [1, 2, 3, 4]
            orig_k = np.asarray(handlers.copier.k_cache[:, pages])
            span = FileSpan(file_key=0xF11E, head_offset=0,
                            blocks=[[p] for p in pages])
            res = wait_results(handlers, handlers.async_store_spans([span]))
            assert res.success
            # One file on disk holding all four slots plus the CRC footer.
            path = handlers.mapper.block_path(0xF11E, 0)
            import os
            assert os.path.getsize(path) == (
                handlers.file_bytes + handlers.footer_bytes())

            handlers.copier.k_cache = handlers.copier.k_cache.at[:, pages].set(0)
            handlers.copier.v_cache = handlers.copier.v_cache.at[:, pages].set(0)
            res2 = wait_results(handlers, handlers.async_load_spans([span]))
            assert res2.success
            np.testing.assert_array_equal(
                np.asarray(handlers.copier.k_cache[:, pages]), orig_k)
        finally:
            handlers.shutdown()

    def test_partial_read_at_head_offset(self, tmp_path):
        """Store a full 4-block file, then load only slots 2-3 (a read
        starting at a nonzero byte offset into the file)."""
        spec, handlers = make_handlers(tmp_path)
        try:
            pages = [1, 2, 3, 4]
            orig_k = np.asarray(handlers.copier.k_cache[:, [3, 4]])
            orig_v = np.asarray(handlers.copier.v_cache[:, [3, 4]])
            full = FileSpan(file_key=0xF22E, head_offset=0,
                            blocks=[[p] for p in pages])
            assert wait_results(handlers, handlers.async_store_spans([full])).success

            handlers.copier.k_cache = handlers.copier.k_cache.at[:, [3, 4]].set(0)
            handlers.copier.v_cache = handlers.copier.v_cache.at[:, [3, 4]].set(0)
            partial = FileSpan(file_key=0xF22E, head_offset=2,
                               blocks=[[3], [4]])
            res = wait_results(handlers, handlers.async_load_spans([partial]))
            assert res.success
            assert res.bytes_transferred == 2 * handlers.slot_bytes
            np.testing.assert_array_equal(
                np.asarray(handlers.copier.k_cache[:, [3, 4]]), orig_k)
            np.testing.assert_array_equal(
                np.asarray(handlers.copier.v_cache[:, [3, 4]]), orig_v)
        finally:
            handlers.shutdown()

    def test_split_spans_covering_file_store_atomically(self, tmp_path):
        """One job may split a file across spans as long as their union
        covers every slot; the file publishes once, fully written."""
        spec, handlers = make_handlers(tmp_path)
        try:
            orig = {p: (np.asarray(handlers.copier.k_cache[:, [p]]),
                        np.asarray(handlers.copier.v_cache[:, [p]]))
                    for p in (1, 2, 3, 4)}
            first = FileSpan(file_key=0xF33E, head_offset=0, blocks=[[1], [2]])
            second = FileSpan(file_key=0xF33E, head_offset=2, blocks=[[3], [4]])
            assert wait_results(
                handlers, handlers.async_store_spans([second, first])).success

            wipe = [1, 2, 3, 4]
            handlers.copier.k_cache = handlers.copier.k_cache.at[:, wipe].set(0)
            handlers.copier.v_cache = handlers.copier.v_cache.at[:, wipe].set(0)
            full = FileSpan(file_key=0xF33E, head_offset=0,
                            blocks=[[p] for p in wipe])
            assert wait_results(handlers, handlers.async_load_spans([full])).success
            for p, (ok, ov) in orig.items():
                np.testing.assert_array_equal(
                    np.asarray(handlers.copier.k_cache[:, [p]]), ok)
                np.testing.assert_array_equal(
                    np.asarray(handlers.copier.v_cache[:, [p]]), ov)
        finally:
            handlers.shutdown()

    def test_partial_store_rejected(self, tmp_path):
        """Stores that leave holes are refused: file existence is the
        lookup predicate, so sparse files would serve zeros as hits."""
        spec, handlers = make_handlers(tmp_path)
        try:
            with pytest.raises(ValueError, match="publish atomically"):
                handlers.async_store_spans([
                    FileSpan(file_key=0xF44E, head_offset=2,
                             blocks=[[1], [2]])])
            import os
            assert not os.path.exists(handlers.mapper.block_path(0xF44E, 0))
        finally:
            handlers.shutdown()

    def test_span_spanning_two_files_via_mapping(self, tmp_path):
        """End-to-end through map_blocks_to_file_spans: logical range
        [2, 6) over 4-block files -> tail of file A + head of file B."""
        spec, handlers = make_handlers(tmp_path)
        try:
            # Pre-fill both files fully so partial loads have backing data.
            a_pages, b_pages = [1, 2, 3, 4], [5, 6, 7, 8]
            for key, pages in ((0xA, a_pages), (0xB, b_pages)):
                span = FileSpan(file_key=key, head_offset=0,
                                blocks=[[p] for p in pages])
                assert wait_results(
                    handlers, handlers.async_store_spans([span])).success

            # Logical blocks 2..5 live in file A slots 2-3 + file B slots 0-1,
            # holding pages 3,4,5,6.
            target = [3, 4, 5, 6]
            orig = np.asarray(handlers.copier.k_cache[:, target])
            handlers.copier.k_cache = handlers.copier.k_cache.at[:, target].set(0)
            handlers.copier.v_cache = handlers.copier.v_cache.at[:, target].set(0)
            spans = map_blocks_to_file_spans(
                [0xA, 0xB], start_block_idx=2,
                blocks=[[p] for p in target], blocks_per_file=4,
            )
            assert wait_results(handlers, handlers.async_load_spans(spans)).success
            np.testing.assert_array_equal(
                np.asarray(handlers.copier.k_cache[:, target]), orig)
        finally:
            handlers.shutdown()

    def test_bad_span_geometry_raises(self, tmp_path):
        spec, handlers = make_handlers(tmp_path)
        try:
            with pytest.raises(ValueError, match="exceeds"):
                handlers.async_store_spans([
                    FileSpan(file_key=1, head_offset=3, blocks=[[1], [2]])])
            with pytest.raises(ValueError, match="pages"):
                handlers.async_store_spans([
                    FileSpan(file_key=1, head_offset=0, blocks=[[1, 2]])])
        finally:
            handlers.shutdown()

    def test_fingerprint_covers_file_geometry(self, tmp_path):
        s1, h1 = make_handlers(tmp_path, blocks_per_file=1)
        s4, h4 = make_handlers(tmp_path, blocks_per_file=4)
        try:
            # A bpf=1 deployment must not read bpf=4 files...
            assert s1.build_mapper().fingerprint != s4.build_mapper().fingerprint
            # ...nor may different slot sizes share a directory.
            s4b = SharedStorageOffloadSpec(
                root=str(tmp_path), model_name="m", page_size=4,
                num_layers=2, kv_heads=2, head_dim=8,
                blocks_per_file=4, pages_per_block=2,
            )
            assert s4.build_mapper().fingerprint != s4b.build_mapper().fingerprint
        finally:
            h1.shutdown()
            h4.shutdown()


class TestNativeWriteAt:
    def test_write_at_primitive(self, tmp_path):
        """The in-place range-write primitive (building block for future
        multi-group slot layouts; not used by the atomic store path)."""
        import os

        from llmd_kv_cache_tpu.offload.native import NativeIOEngine
        from tests.test_offload import wait_finished

        engine = NativeIOEngine(num_threads=1)
        try:
            path = str(tmp_path / "multi.bin")
            a = np.full(100, 1, dtype=np.uint8)
            b = np.full(100, 2, dtype=np.uint8)
            job = engine.begin_job()
            assert engine.submit_write_at(job, path, a, offset=0, file_size=300)
            assert engine.submit_write_at(job, path, b, offset=200, file_size=300)
            engine.seal_job(job)
            assert wait_finished(engine, job) == 0
            assert os.path.getsize(path) == 300
            out = np.fromfile(path, dtype=np.uint8)
            np.testing.assert_array_equal(out[:100], a)
            np.testing.assert_array_equal(out[200:], b)
            assert (out[100:200] == 0).all()  # unwritten hole stays zero
        finally:
            engine.close()
