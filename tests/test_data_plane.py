"""Native data plane (docs/architecture.md "Native data plane"): packed
zero-copy event frames, the shared-memory event ring, the pool's
sniff-and-dispatch ingest path, and the indexer's native chunked scoring —
each checked for exact equivalence against the msgpack / pure-Python
paths it replaces.
"""

import time

import msgpack
import numpy as np
import pytest

from llmd_kv_cache_tpu.core import (
    ChunkedTokenDatabase,
    PodEntry,
    TokenProcessorConfig,
)
from llmd_kv_cache_tpu.events import Pool, PoolConfig, RawMessage
from llmd_kv_cache_tpu.events.packed import (
    HEADER_SIZE,
    decode_packed_batch,
    encode_packed_batch,
    is_packed,
)
from llmd_kv_cache_tpu.events.shm_ring import ShmRing
from llmd_kv_cache_tpu.index import InMemoryIndex, InMemoryIndexConfig
from llmd_kv_cache_tpu.index import native

BLOCK = 4
MODEL = "model-a"
POD = "pod-1"


@pytest.fixture
def processor():
    return ChunkedTokenDatabase(TokenProcessorConfig(block_size_tokens=BLOCK))


@pytest.fixture
def index():
    return InMemoryIndex(InMemoryIndexConfig(size=10_000))


@pytest.fixture
def pool(index, processor):
    return Pool(PoolConfig(concurrency=2), index, processor)


def packed_msg(pod, model, engine_keys, tokens, *, parent=0, seq=0, ts=1.0):
    payload = encode_packed_batch(
        pod, model, engine_keys, tokens,
        timestamp=ts, parent_hash=parent, block_size=BLOCK,
    )
    return RawMessage(topic=f"kv@{pod}@{model}", sequence=seq, payload=payload)


def msgpack_msg(pod, model, engine_keys, tokens, *, parent=0, seq=0, ts=1.0):
    ev = ["BlockStored", list(engine_keys), parent or None, list(tokens), BLOCK]
    payload = msgpack.packb([ts, [ev]], use_bin_type=True)
    return RawMessage(topic=f"kv@{pod}@{model}", sequence=seq, payload=payload)


class TestPackedCodec:
    def test_round_trip(self):
        eks = [2**63 + 1, 7, 0xFFFFFFFFFFFFFFFF]
        toks = list(range(12))
        payload = encode_packed_batch(
            POD, MODEL, eks, toks,
            timestamp=123.5, parent_hash=42, block_size=BLOCK,
        )
        assert is_packed(payload)
        pb = decode_packed_batch(payload)
        assert pb.pod_id == POD
        assert pb.model_name == MODEL
        assert pb.timestamp == 123.5
        assert pb.parent_hash == 42
        assert pb.block_size == BLOCK
        assert pb.engine_keys.dtype == np.uint64
        assert pb.tokens.dtype == np.uint32
        assert pb.engine_keys.tolist() == eks
        assert pb.tokens.tolist() == toks

    def test_views_are_zero_copy(self):
        payload = encode_packed_batch(POD, MODEL, [1], [1, 2, 3, 4],
                                      timestamp=1.0)
        pb = decode_packed_batch(payload)
        # numpy views over the frame buffer, not copies.
        assert pb.engine_keys.base is not None
        assert pb.tokens.base is not None

    def test_empty_arrays(self):
        pb = decode_packed_batch(
            encode_packed_batch(POD, MODEL, [], [], timestamp=0.0)
        )
        assert len(pb.engine_keys) == 0 and len(pb.tokens) == 0

    def test_unicode_strings_pad_to_alignment(self):
        pod, model = "pod-é", "m/✓"
        pb = decode_packed_batch(
            encode_packed_batch(pod, model, [9], [1], timestamp=2.0)
        )
        assert (pb.pod_id, pb.model_name) == (pod, model)
        assert pb.engine_keys.tolist() == [9]

    @pytest.mark.parametrize("payload", [
        b"",
        b"KZC1",
        b"XXXX" + b"\0" * 64,
        encode_packed_batch(POD, MODEL, [1, 2], [1], timestamp=1.0)[:-8],
    ])
    def test_malformed_frames_raise(self, payload):
        with pytest.raises(ValueError):
            decode_packed_batch(payload)

    def test_is_packed_sniff(self):
        assert not is_packed(b"")
        assert not is_packed(b"KZC")
        assert not is_packed(msgpack.packb([1.0, []], use_bin_type=True))
        assert is_packed(b"KZC1garbage")  # sniff only; decode rejects later

    def test_header_size_pinned(self):
        # The wire layout is cross-version state: 36 bytes, by contract.
        assert HEADER_SIZE == 36


class TestZeroCopyIngest:
    """Packed-frame ingest must leave the index in the byte-identical
    state the msgpack BlockStored wire produces."""

    def _states(self, idx, processor, tokens, engine_keys):
        rks = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        return (idx.lookup(rks),
                {ek: idx.get_request_key(ek) for ek in engine_keys})

    def test_matches_msgpack_wire(self, processor):
        tokens = list(range(8))
        eks = [101, 102]
        idx_packed = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        idx_msgpack = InMemoryIndex(InMemoryIndexConfig(size=10_000))
        Pool(PoolConfig(concurrency=1), idx_packed, processor) \
            ._process_raw_message(packed_msg(POD, MODEL, eks, tokens))
        Pool(PoolConfig(concurrency=1), idx_msgpack, processor) \
            ._process_raw_message(msgpack_msg(POD, MODEL, eks, tokens))
        assert self._states(idx_packed, processor, tokens, eks) == \
            self._states(idx_msgpack, processor, tokens, eks)
        rks = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        assert idx_packed.lookup(rks)[rks[0]] == [PodEntry(POD, "tpu-hbm")]

    @pytest.mark.skipif(not native.native_available(),
                        reason="native library unavailable")
    def test_matches_msgpack_wire_on_native_index(self, processor):
        from llmd_kv_cache_tpu.index.native import NativeIndex, NativeIndexConfig

        tokens = list(range(16))
        eks = [301, 302, 303, 304]
        idx_packed = NativeIndex(NativeIndexConfig(size=10_000))
        idx_msgpack = NativeIndex(NativeIndexConfig(size=10_000))
        pool = Pool(PoolConfig(concurrency=1), idx_packed, processor)
        pool._process_raw_message(packed_msg(POD, MODEL, eks, tokens))
        Pool(PoolConfig(concurrency=1), idx_msgpack, processor) \
            ._process_raw_message(msgpack_msg(POD, MODEL, eks, tokens))
        assert self._states(idx_packed, processor, tokens, eks) == \
            self._states(idx_msgpack, processor, tokens, eks)
        assert pool.zerocopy_batches == 1

    def test_parent_chain_resolution(self, pool, index, processor):
        t1, t2 = list(range(4)), list(range(4, 8))
        pool._process_raw_message(packed_msg(POD, MODEL, [11], t1))
        pool._process_raw_message(
            packed_msg(POD, MODEL, [12], t2, parent=11, seq=1)
        )
        full_keys = processor.tokens_to_kv_block_keys(0, t1 + t2, MODEL)
        assert set(index.lookup(full_keys)) == set(full_keys)

    def test_unknown_parent_drops_frame(self, pool, index, processor):
        pool._process_raw_message(
            packed_msg(POD, MODEL, [12], list(range(4)), parent=999)
        )
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        assert index.lookup(rk) == {}
        # The frame decoded fine — the drop is a chain-resolution decision,
        # so it still counts as a zero-copy batch.
        assert pool.zerocopy_batches == 1

    def test_kill_switch_disables_packed_decode(self, index, processor):
        pool = Pool(
            PoolConfig(concurrency=1, ingest_zero_copy=False),
            index, processor,
        )
        pool._process_raw_message(packed_msg(POD, MODEL, [1], list(range(4))))
        rk = processor.tokens_to_kv_block_keys(0, list(range(4)), MODEL)
        assert index.lookup(rk) == {}  # parse failure, not an ingest
        assert pool.zerocopy_batches == 0

    def test_malformed_frame_does_not_kill_ingest(self, pool, index, processor):
        pool._process_raw_message(RawMessage(
            topic=f"kv@{POD}@{MODEL}", sequence=0, payload=b"KZC1truncated"
        ))
        tokens = list(range(4))
        pool._process_raw_message(packed_msg(POD, MODEL, [81], tokens, seq=1))
        rk = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
        assert index.lookup(rk) != {}
        assert pool.zerocopy_batches == 1

    def test_counters_and_debug_view(self, pool, index, processor):
        for i in range(3):
            pool._process_raw_message(
                packed_msg(POD, MODEL, [900 + i],
                           list(range(4 * i, 4 * i + 4)), seq=i)
            )
        dp = pool.data_plane_debug()
        assert dp["zerocopy_batches"] == 3
        assert dp["shm_messages"] == 0

    def test_lag_tracked_from_packed_timestamp(self, pool, processor):
        pool._process_raw_message(
            packed_msg(POD, MODEL, [1], list(range(4)),
                       ts=time.time() - 2.0)
        )
        assert pool.lag_stats()["pods"][POD]["lag_s"] >= 2.0

    def test_full_pipeline_through_sharded_workers(self, index, processor):
        pool = Pool(PoolConfig(concurrency=4), index, processor)
        pool.start()
        try:
            tokens = list(range(8))
            pool.add_task(packed_msg(POD, MODEL, [71, 72], tokens))
            pool.join()
            rks = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
            assert set(index.lookup(rks)) == set(rks)
            assert pool.zerocopy_batches == 1
        finally:
            pool.shutdown()


class TestCoalescerHoist:
    def test_multi_digest_single_message_coalesces(self, pool, index, processor):
        """One message carrying several 1:1 BlockStored digests merges
        into one index add (the per-worker persistent-coalescer change
        made single-message batches coalesce too)."""
        ev1 = ["BlockStored", [201], None, list(range(4)), BLOCK]
        ev2 = ["BlockStored", [202], None, list(range(10, 14)), BLOCK]
        payload = msgpack.packb([1.0, [ev1, ev2]], use_bin_type=True)
        pool._process_raw_batch(
            [RawMessage(topic=f"kv@{POD}@{MODEL}", sequence=0, payload=payload)]
        )
        assert pool.coalesced_ops >= 1
        for toks in (list(range(4)), list(range(10, 14))):
            rk = processor.tokens_to_kv_block_keys(0, toks, MODEL)
            assert index.lookup(rk) != {}

    def test_worker_coalescer_persists_across_batches(self, index, processor):
        pool = Pool(PoolConfig(concurrency=1), index, processor)
        pool.start()
        try:
            for i in range(4):
                ev1 = ["BlockStored", [300 + 2 * i], None,
                       list(range(8 * i, 8 * i + 4)), BLOCK]
                ev2 = ["BlockStored", [301 + 2 * i], None,
                       list(range(8 * i + 4, 8 * i + 8)), BLOCK]
                pool.add_task(RawMessage(
                    topic=f"kv@{POD}@{MODEL}", sequence=i,
                    payload=msgpack.packb([1.0, [ev1, ev2]], use_bin_type=True),
                ))
            pool.join()
            assert pool.coalesced_ops >= 4
            for start in range(0, 32, 4):
                rks = processor.tokens_to_kv_block_keys(
                    0, list(range(start, start + 4)), MODEL)
                assert set(index.lookup(rks)) == set(rks), start
        finally:
            pool.shutdown()


class TestShmRing:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "ring")
        w = ShmRing(path, capacity=1 << 14, create=True)
        r = ShmRing(path)
        try:
            records = [bytes([i]) * (50 + i) for i in range(5)]
            for rec in records:
                assert w.write(rec)
            for rec in records:
                assert r.read() == rec
            assert r.read() is None
            assert len(r) == 0
        finally:
            r.close()
            w.close()

    def test_wrap_preserves_order_via_skip_marker(self, tmp_path):
        path = str(tmp_path / "ring")
        w = ShmRing(path, capacity=4096, create=True)
        r = ShmRing(path)
        try:
            # Records of ~1500B force a skip-marker wrap every few writes.
            for i in range(50):
                rec = bytes([i % 251]) * 1500
                assert w.write(rec), i
                assert r.read() == rec, i
        finally:
            r.close()
            w.close()

    def test_full_ring_drops_at_writer_then_recovers(self, tmp_path):
        path = str(tmp_path / "ring")
        w = ShmRing(path, capacity=4096, create=True)
        r = ShmRing(path)
        try:
            rec = b"x" * 1000
            written = 0
            while w.write(rec):
                written += 1
            assert 0 < written < 10  # bounded by capacity, never blocks
            for _ in range(written):
                assert r.read() == rec
            assert r.read() is None
            assert w.write(rec)  # space reclaimed once the reader caught up
        finally:
            r.close()
            w.close()

    def test_oversize_record_rejected(self, tmp_path):
        w = ShmRing(str(tmp_path / "ring"), capacity=4096, create=True)
        try:
            assert not w.write(b"y" * 4096)
        finally:
            w.close()

    def test_reader_rejects_foreign_file(self, tmp_path):
        path = tmp_path / "not-a-ring"
        path.write_bytes(b"\0" * 128)
        with pytest.raises(ValueError):
            ShmRing(str(path))

    def test_unlink(self, tmp_path):
        import os

        path = str(tmp_path / "ring")
        w = ShmRing(path, capacity=4096, create=True)
        w.close()
        ShmRing.unlink(ShmRing(path))  # attach works before unlink
        assert not os.path.exists(path)

    def test_pool_drains_ring_end_to_end(self, tmp_path, index, processor):
        path = str(tmp_path / "ring")
        ring = ShmRing(path, capacity=1 << 16, create=True)
        pool = Pool(
            PoolConfig(concurrency=2, shm_ring_path=path,
                       shm_ring_poll_s=0.0005),
            index, processor,
        )
        pool.start()
        try:
            tokens = list(range(8))
            frame = encode_packed_batch(
                POD, MODEL, [101, 102], tokens,
                timestamp=time.time(), block_size=BLOCK,
            )
            assert ring.write(frame)
            rks = processor.tokens_to_kv_block_keys(0, tokens, MODEL)
            deadline = time.time() + 10.0
            while time.time() < deadline and index.lookup(rks) == {}:
                time.sleep(0.005)
            assert set(index.lookup(rks)) == set(rks)
            dp = pool.data_plane_debug()
            assert dp["shm_messages"] == 1
            assert dp["zerocopy_batches"] == 1
        finally:
            pool.shutdown()
            ring.close()


@pytest.mark.skipif(not native.native_available(),
                    reason="native library unavailable")
class TestIndexerNativeChunkedEquivalence:
    """The indexer's `_score_native_chunked` dispatch must score exactly
    like the pure-Python path — base scores, liveness ordering, residency
    bonus, and detail threading included."""

    def _pair(self, chunk_size=4):
        from llmd_kv_cache_tpu.index.native import NativeIndex, NativeIndexConfig
        from llmd_kv_cache_tpu.scoring.indexer import Indexer, IndexerConfig

        def make(index):
            return Indexer(
                IndexerConfig(
                    token_processor_config=TokenProcessorConfig(
                        block_size_tokens=BLOCK),
                    lookup_chunk_size=chunk_size,
                ),
                index=index,
            )

        nat = make(NativeIndex(NativeIndexConfig(size=10_000)))
        py = make(InMemoryIndex(InMemoryIndexConfig(size=10_000)))
        assert nat._native_score_chunked is not None
        assert py._native_score_chunked is None
        return nat, py

    def _seed(self, indexers, tokens, placements):
        keys = indexers[0].compute_block_keys(tokens, MODEL)
        for pod, n_blocks, tier in placements:
            for ix in indexers:
                ix.kv_block_index.add(
                    None, keys[:n_blocks], [PodEntry(pod, tier)]
                )
        return keys

    def test_scores_identical_across_roles_and_filters(self):
        nat, py = self._pair()
        tokens = list(range(48))  # 12 blocks
        self._seed((nat, py), tokens, [
            ("pod-a", 12, "tpu-hbm"),
            ("pod-b", 7, "cpu"),
            ("pod-c", 3, "shared_storage"),
        ])
        for pods in (None, ["pod-a", "pod-c"], ["nope"]):
            for role in ("", "decode"):
                assert nat.score_tokens(tokens, MODEL, pods, role=role) == \
                    py.score_tokens(tokens, MODEL, pods, role=role), (pods, role)
        assert nat.data_plane_debug()["native_score_calls"] > 0

    def test_residency_bonus_and_detail_identical(self):
        from llmd_kv_cache_tpu.scoring.residency import ResidencyTracker

        nat, py = self._pair()
        tokens = list(range(32))  # 8 blocks
        keys = self._seed((nat, py), tokens, [("pod-a", 8, "tpu-hbm")])
        for ix in (nat, py):
            tracker = ResidencyTracker(in_flight_discount=0.5)
            tracker.on_landed("decode-0", keys[:5])
            tracker.on_transfer_started("decode-1", keys[:8])
            ix.attach_residency(tracker)
        detail_nat, detail_py = {}, {}
        s_nat = nat.score_tokens(tokens, MODEL, role="decode",
                                 detail=detail_nat)
        s_py = py.score_tokens(tokens, MODEL, role="decode",
                               detail=detail_py)
        assert s_nat == s_py
        assert detail_nat["residency"] == detail_py["residency"]
        assert detail_nat["residency"]["decode-0"] == pytest.approx(5.0)
        # Role-agnostic requests must not leak the bonus.
        assert nat.score_tokens(tokens, MODEL) == py.score_tokens(tokens, MODEL)

    def test_early_exit_equivalence_with_chain_hole(self):
        nat, py = self._pair(chunk_size=2)
        tokens = list(range(40))  # 10 blocks
        keys = self._seed((nat, py), tokens, [("pod-a", 10, "tpu-hbm")])
        from llmd_kv_cache_tpu.core import KeyType

        for ix in (nat, py):
            ix.kv_block_index.evict(
                keys[5], KeyType.REQUEST, [PodEntry("pod-a", "tpu-hbm")]
            )
        assert nat.score_tokens(tokens, MODEL) == py.score_tokens(tokens, MODEL)
        dp = nat.data_plane_debug()
        assert dp["native_score_early_exits"] == 1
        assert 0 < dp["native_score_chunks"] < 5  # stopped before chunk 5

    def test_chunking_disabled_still_equivalent(self):
        nat, py = self._pair(chunk_size=0)
        tokens = list(range(24))
        self._seed((nat, py), tokens, [("pod-a", 6, "tpu-hbm"),
                                       ("pod-b", 2, "cpu")])
        assert nat.score_tokens(tokens, MODEL) == py.score_tokens(tokens, MODEL)
