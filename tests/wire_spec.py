"""Spec-derived msgpack byte assembly for foreign-wire golden fixtures.

The adapters (``events/adapters/vllm.py``) decode with msgpack-python, and
the repo's earlier fixtures were *encoded* with msgpack-python too — so an
encoder/decoder quirk shared by that one library would pass the suite and
fail against a real engine (VERDICT r2, missing #1). The byte strings here
are assembled by hand from the msgpack format specification
(msgpack/spec.md: format byte + big-endian payload), NOT produced by any
msgpack library, and they replicate the encoding decisions of the two
foreign encoders on the real wire:

- **msgspec** (vLLM's serializer, ``array_like=True, omit_defaults=True``):
  structs as fixed arrays with the tag at position 0, trailing default
  fields omitted (shorter arrays), ints in the shortest unsigned form when
  >= 0 / shortest signed otherwise, ``time.time()`` timestamps as float64,
  raw digests as bin, None as nil.
- **vmihailenco/msgpack v5** (the encoder the reference's own adapter tests
  use, ``vllm_adapter_test.go:25,56``): same shortest-form integer rules;
  the full-fixture vectors below mirror that file's semantic test values
  (hashes 100/101, parent 99, tokens 1-3, block 16, "gpu") so parity with
  the Go tests is line-checkable.

``fixtures()`` returns the committed golden set; ``tests/assets/wire/*.bin``
must be byte-identical (asserted by test_wire_fixtures.py — regenerate with
``python hack/gen_wire_fixtures.py`` only when adding fixtures).
"""

from __future__ import annotations

import struct

# --- msgpack spec primitives (format-byte + big-endian, per spec.md) ---


def nil() -> bytes:
    return b"\xc0"


def u(n: int) -> bytes:
    """Shortest unsigned form — what msgspec and vmihailenco emit for >= 0."""
    if n < 0:
        return i(n)
    if n < 0x80:
        return bytes([n])  # positive fixint
    if n <= 0xFF:
        return b"\xcc" + bytes([n])
    if n <= 0xFFFF:
        return b"\xcd" + struct.pack(">H", n)
    if n <= 0xFFFFFFFF:
        return b"\xce" + struct.pack(">I", n)
    return b"\xcf" + struct.pack(">Q", n)


def i(n: int) -> bytes:
    """Shortest signed form for negatives (Python hash() can be negative)."""
    if n >= 0:
        return u(n)
    if n >= -32:
        return struct.pack("b", n)  # negative fixint
    if n >= -(2**7):
        return b"\xd0" + struct.pack(">b", n)
    if n >= -(2**15):
        return b"\xd1" + struct.pack(">h", n)
    if n >= -(2**31):
        return b"\xd2" + struct.pack(">i", n)
    return b"\xd3" + struct.pack(">q", n)


def u16_wide(n: int) -> bytes:
    """Fixed-width uint16 even for small values — spec-legal, emitted by
    typed encoders (a Go uint16 field), never by msgpack-python's packb."""
    return b"\xcd" + struct.pack(">H", n)


def u32_wide(n: int) -> bytes:
    """Fixed-width uint32 for small values (see u16_wide)."""
    return b"\xce" + struct.pack(">I", n)


def f64(x: float) -> bytes:
    return b"\xcb" + struct.pack(">d", x)


def s(text: str) -> bytes:
    raw = text.encode("utf-8")
    if len(raw) <= 31:
        return bytes([0xA0 | len(raw)]) + raw  # fixstr
    if len(raw) <= 0xFF:
        return b"\xd9" + bytes([len(raw)]) + raw  # str 8
    raise ValueError("fixture strings are short")


def binary(data: bytes) -> bytes:
    if len(data) <= 0xFF:
        return b"\xc4" + bytes([len(data)]) + data  # bin 8
    raise ValueError("fixture binaries are short")


def arr(*items: bytes) -> bytes:
    if len(items) <= 15:
        return bytes([0x90 | len(items)]) + b"".join(items)  # fixarray
    if len(items) <= 0xFFFF:
        return b"\xdc" + struct.pack(">H", len(items)) + b"".join(items)
    raise ValueError("fixture arrays are short")


def mp(*pairs: "tuple[bytes, bytes]") -> bytes:
    if len(pairs) <= 15:
        return bytes([0x80 | len(pairs)]) + b"".join(k + v for k, v in pairs)  # fixmap
    raise ValueError("fixture maps are short")


def tru() -> bytes:
    return b"\xc3"


def fal() -> bytes:
    return b"\xc2"


# --- golden fixtures ---

TS = 1234567890.0
# sha256-style digests (deterministic, spelled out — not computed here so the
# expected uint64 tails below are visibly frozen).
DIGEST_A = bytes(range(32))
DIGEST_B = bytes(range(100, 132))


TRACEPARENT = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"


def score_fixtures() -> dict[str, bytes]:
    """Scoring-RPC message bodies (the msgpack gRPC wire of
    ``services.indexer_service``), spec-assembled like the event payloads.

    Wire-compat contract for the sharded control plane: ScoreRequest/
    ScoreResponse grew optional shard metadata (``shard``,
    ``degraded_shards``) the same tolerant way ``degraded``/``traceparent``
    arrived — the *legacy* fixtures prove an old peer's bytes still decode
    (absent keys default), the *shard* fixtures prove the new fields
    round-trip and that unknown future keys are ignored.
    """
    return {
        # Old scheduler → new server: no shard/traceparent/degraded keys.
        "score_request_legacy.bin": mp(
            (s("tokens"), arr(u(1), u(2), u(3))),
            (s("model_name"), s("llama-2-7b")),
            (s("pod_identifiers"), arr(s("pod-1"), s("pod-2"))),
        ),
        # New-style request with shard metadata plus an unknown key a
        # *future* peer might add — decoders must ignore it.
        "score_request_shard.bin": mp(
            (s("tokens"), arr(u(7), u(8))),
            (s("model_name"), s("llama-2-7b")),
            (s("pod_identifiers"), arr()),
            (s("shard"), s("shard-1")),
            (s("future_hint"), nil()),
        ),
        # Old server → new scheduler: scores + error only.
        "score_response_legacy.bin": mp(
            (s("scores"), mp((s("pod-1"), f64(0.5)))),
            (s("error"), s("")),
        ),
        # New shard-aware response: degraded fan-out with shard metadata.
        "score_response_shard.bin": mp(
            (s("scores"), mp((s("pod-1"), f64(0.75)), (s("pod-2"), f64(0.25)))),
            (s("error"), s("")),
            (s("degraded"), tru()),
            (s("traceparent"), s(TRACEPARENT)),
            (s("shard"), s("shard-0")),
            (s("degraded_shards"), arr(s("shard-2"))),
        ),
        # Disaggregated decode pod asking for residency-aware scores: the
        # ``role`` key arrives the same tolerant way ``shard`` did (plus an
        # unknown future key decoders must ignore).
        "score_request_role.bin": mp(
            (s("tokens"), arr(u(1), u(2), u(3), u(4))),
            (s("model_name"), s("llama-2-7b")),
            (s("pod_identifiers"), arr(s("decode-1"), s("decode-2"))),
            (s("role"), s("decode")),
            (s("handoff_hint"), nil()),
        ),
        # Residency-aware response: per-pod residency bonus detail rides
        # alongside the merged scores (handoff coordinator input).
        "score_response_residency.bin": mp(
            (s("scores"), mp((s("decode-1"), f64(1.5)), (s("decode-2"), f64(0.25)))),
            (s("error"), s("")),
            (s("traceparent"), s(TRACEPARENT)),
            (s("residency"), mp((s("decode-1"), f64(1.25)))),
        ),
        # Gray-failure plane: end-to-end deadline budget + shed priority
        # arrive the same tolerant way shard/role did — a ms budget (never
        # an absolute timestamp: clocks skew, budgets don't) and an int
        # priority class, plus an unknown future key decoders must ignore.
        "score_request_deadline.bin": mp(
            (s("tokens"), arr(u(11), u(12), u(13))),
            (s("model_name"), s("llama-2-7b")),
            (s("pod_identifiers"), arr(s("pod-1"))),
            (s("deadline_ms"), u(250)),
            (s("priority"), u(2)),
            (s("hedge_hint"), nil()),
        ),
        # Brownout response: served, but flagged degraded with the reason
        # the overload shedder attached (residency fold-in skipped).
        "score_response_brownout.bin": mp(
            (s("scores"), mp((s("pod-1"), f64(0.5)))),
            (s("error"), s("")),
            (s("degraded"), tru()),
            (s("degraded_reason"), s("brownout")),
        ),
        # Ground-truth audit plane: the ScoreFeedback a scheduler builds
        # from the response it routed on and hands to the chosen engine —
        # every field arrives the same tolerant way residency/shard did.
        "score_feedback_full.bin": mp(
            (s("traceparent"), s(TRACEPARENT)),
            (s("chosen_pod"), s("pod-1")),
            (s("predicted_blocks"), f64(3.5)),
            (s("total_blocks"), u(8)),
            (s("scores"), mp((s("pod-1"), f64(3.5)), (s("pod-2"), f64(1.0)))),
            (s("residency"), mp((s("pod-1"), f64(0.5)))),
            (s("staleness_s"), f64(0.25)),
        ),
        # A minimal/older peer's feedback: only the join key and the
        # chosen pod, an integer-typed prediction (Go encoders emit the
        # shortest int form for whole values), and an unknown future key
        # decoders must ignore.
        "score_feedback_legacy.bin": mp(
            (s("traceparent"), s(TRACEPARENT)),
            (s("chosen_pod"), s("pod-1")),
            (s("predicted_blocks"), u(3)),
            (s("audit_hint"), nil()),
        ),
        # Epoch-fenced topology plane: the monotonic fleet epoch arrives
        # the same tolerant way ``deadline_ms`` did — epoch 0 / absent
        # means an unstamped legacy peer and is never fenced, so the
        # legacy fixtures above double as the old-peer half of the
        # warn-mode interop proof. Unknown future key must be ignored.
        "score_request_epoch.bin": mp(
            (s("tokens"), arr(u(1), u(2), u(3))),
            (s("model_name"), s("llama-2-7b")),
            (s("pod_identifiers"), arr(s("pod-1"))),
            (s("epoch"), u(7)),
            (s("lease_hint"), nil()),
        ),
        # Fenced response (fenceMode: reject): shed-shaped, with the
        # receiver's own newer epoch stamped so the stale sender learns
        # the bump from the refusal itself (gossip-by-piggyback).
        "score_response_fenced.bin": mp(
            (s("scores"), mp()),
            (s("error"), s("stale topology epoch 6 (fleet at 7)")),
            (s("degraded"), tru()),
            (s("degraded_reason"), s("fenced")),
            (s("epoch"), u(7)),
        ),
        # Shard-RPC lookup frame with the epoch stamp riding next to the
        # deadline budget: pre-epoch shards ignore the key, post-epoch
        # shards fence on it.
        "lookup_request_epoch.bin": mp(
            (s("keys"), arr(u(100), u(101))),
            (s("pods"), arr(s("pod-1"))),
            (s("deadline_ms"), u(40)),
            (s("epoch"), u(7)),
        ),
        # Shard-RPC lookup frame with deadline + hedge markers (the
        # cluster.remote frame wire): old shards ignore both keys.
        "lookup_request_deadline.bin": mp(
            (s("keys"), arr(u(100), u(101))),
            (s("pods"), arr(s("pod-1"))),
            (s("deadline_ms"), u(40)),
            (s("hedge"), tru()),
        ),
        # Batched multi-chunk lookup frame (the native data plane): one
        # RPC carries a whole gather window of early-exit chunks, with
        # the same tolerant deadline/hedge metadata the flat frame grew.
        # A pre-batch server answers this method UNIMPLEMENTED (the
        # router's fallback cue); a flat ``keys`` frame reaching the new
        # handler is treated as one implicit chunk.
        "lookup_batch_request.bin": mp(
            (s("chunks"), arr(
                arr(u(100), u(101)),
                arr(u(102), u(103)),
            )),
            (s("pods"), arr(s("pod-1"))),
            (s("deadline_ms"), u(40)),
            (s("hedge"), tru()),
        ),
        # Batched response: chunk 0 complete (cont=1), chunk 1 missing a
        # key (cont=0) — the shard early-exited server-side, so no third
        # chunk rides the frame. Rows are the LookupBlocks
        # ``[key, [[pod, tier, flags, group_idx], ...]]`` layout.
        "lookup_batch_response.bin": mp(
            (s("chunks"), arr(
                arr(
                    arr(u(100), arr(arr(s("pod-1"), s("tpu-hbm"), u(0), nil()))),
                    arr(u(101), arr(arr(s("pod-1"), s("tpu-hbm"), u(0), nil()))),
                ),
                arr(
                    arr(u(102), arr(arr(s("pod-2"), s("tpu-hbm"), u(0), nil()))),
                ),
            )),
            (s("cont"), arr(u(1), u(0))),
            (s("degraded"), fal()),
            (s("shard"), s("shard-0")),
        ),
        # Old-frame tolerance, response direction: a flat LookupBlocks
        # body (no chunks/cont) that a batch-aware client must read as
        # one implicit chunk with every answered key counting.
        "lookup_batch_response_flat.bin": mp(
            (s("hits"), arr(
                arr(u(100), arr(arr(s("pod-1"), s("tpu-hbm"), u(0), nil()))),
            )),
            (s("degraded"), fal()),
            (s("shard"), s("shard-0")),
        ),
    }


def fixtures() -> dict[str, bytes]:
    """name → committed payload bytes: ZMQ event payloads (the third wire
    frame) plus the scoring-RPC bodies from :func:`score_fixtures`."""
    # Reference-mirroring full BlockStored (vllm_adapter_test.go:38-56):
    # 9 fields, parent present, medium "gpu", trailing lora_name/extra nil.
    full_stored = arr(
        s("BlockStored"), arr(u(100), u(101)), u(99),
        arr(u(1), u(2), u(3)), u(16), nil(), s("gpu"), nil(), nil(),
    )
    # msgspec omit_defaults: trailing defaults dropped → 5-field event,
    # 2-element batch (data_parallel_rank omitted).
    omit_stored = arr(
        s("BlockStored"), arr(u(7)), nil(), arr(u(5), u(6)), u(4),
    )
    # Integer encoding edges: uint64 with the high bit set (0xcf), a
    # negative fixint and an int64 (engines emitting Python hash()), token
    # ids spanning uint8/16/32 forms, dp_rank present.
    int_edges_stored = arr(
        s("BlockStored"),
        arr(u(0xFFFFFFFFFFFFFFFE), i(-3), i(-(2**63) + 8)),
        u(0x8000000000000001),
        arr(u(255), u(65535), u(70000)), u(16),
    )
    # Raw-digest hashes (bin 8): normalized to last-8-bytes big-endian.
    bytes_stored = arr(
        s("BlockStored"), arr(binary(DIGEST_A), binary(DIGEST_B)), nil(),
        arr(u(1)), u(16),
    )
    # Full HMA field set through position 11 (group_idx, spec kind, window).
    hma_stored = arr(
        s("BlockStored"), arr(u(200)), nil(), arr(u(9)), u(16),
        nil(), s("gpu"), nil(),
        arr(arr(s("lora"), u(4))),  # extra_keys
        u(1), s("sliding_window"), u(1024),
    )
    # Spec-legal non-shortest forms: typed encoders emit fixed-width ints
    # for declared-width fields; a msgpack-python round-trip re-encodes
    # these shortest-form, so these bytes CANNOT be a packb artifact.
    wide_stored = arr(
        s("BlockStored"), arr(u32_wide(77)), nil(),
        arr(u16_wide(1), u16_wide(2)), u32_wide(16),
    )
    removed_and_cleared = arr(
        arr(s("BlockRemoved"), arr(u(100), u(101)), s("gpu")),
        arr(s("AllBlocksCleared")),
    )
    # Coherent-token batch for the zmq→pool→index drive: 2 blocks of 4
    # tokens, root parent — the pool recomputes canonical keys from these.
    index_stored = arr(
        s("BlockStored"), arr(u(100), u(101)), nil(),
        arr(*[u(t) for t in range(1, 9)]), u(4), nil(), s("gpu"),
    )
    return {
        # vLLM: payload = [ts, [event...], dp_rank?]
        "vllm_block_stored_full.bin": arr(f64(TS), arr(full_stored), nil()),
        "vllm_omit_defaults.bin": arr(f64(TS), arr(omit_stored)),
        "vllm_int_edges.bin": arr(f64(TS), arr(int_edges_stored), u(3)),
        "vllm_bytes_hashes.bin": arr(f64(TS), arr(bytes_stored), nil()),
        "vllm_wide_ints.bin": arr(f64(TS), arr(wide_stored), nil()),
        "vllm_hma_fields.bin": arr(f64(TS), arr(hma_stored), nil()),
        "vllm_removed_cleared.bin": arr(f64(TS), removed_and_cleared, nil()),
        # Events may arrive bin-embedded (serializer nesting).
        "vllm_nested_bin.bin": arr(f64(TS), arr(binary(full_stored)), nil()),
        # Epoch-stamped batch: wire element [4] after traceparent carries
        # the publisher's topology epoch (cluster.membership); the
        # publisher pads absent middles with nil. Engines that predate
        # the epoch plane send shorter arrays — every fixture above is
        # that legacy case and must keep decoding with epoch 0.
        "vllm_epoch_stamped.bin": arr(
            f64(TS), arr(index_stored), nil(), s(TRACEPARENT), u(7)),
        "vllm_wire_to_index.bin": arr(f64(TS), arr(index_stored), nil()),
        # SGLang: same positional wire, schema ends at extra_keys — a
        # longer array must NOT leak HMA fields into the decode.
        "sglang_block_stored.bin": arr(
            f64(TS),
            arr(arr(
                s("BlockStored"), arr(u(300)), nil(), arr(u(9)), u(16),
                nil(), s("gpu"), nil(), nil(),
                u(1), s("sliding_window"), u(1024),  # beyond SGLang schema
            )),
            nil(),
        ),
        **score_fixtures(),
    }
