#!/usr/bin/env python
"""Offload data-plane throughput: D2H/H2D GB/s and file I/O GB/s.

Measures the two legs of the offload path separately:

1. device->host gather (TPUBlockCopier.gather_many_to_host) and
   host->device scatter — the TPU-side analog of the reference's
   TensorCopier D2H/H2D (tensor_copier.cu:222-249); reports whether the
   pinned_host memory kind was active.
2. kvio file writes/reads (buffered vs O_DIRECT staged), the FileIO leg.

Prints one JSON object with all figures; run on a TPU host for the real
numbers (CPU backend figures are host-memcpy baselines, labeled as such).

Usage: python benchmarking/offload_throughput.py [--pages 64] [--iters 5]
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def bench_copier(pages: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp

    from llmd_kv_cache_tpu.offload.tpu_copier import TPUBlockCopier

    layers, num_pages, kv_heads, page_size, head_dim = 4, pages + 1, 8, 16, 128
    shape = (layers, num_pages, kv_heads, page_size, head_dim)
    k = jnp.zeros(shape, jnp.bfloat16)
    v = jnp.zeros(shape, jnp.bfloat16)
    copier = TPUBlockCopier(k, v)
    page_ids = list(range(1, pages + 1))
    nbytes = copier.slab_nbytes(pages)

    # Warmup (compile + cache)
    slabs = copier.gather_many_to_host([page_ids])
    copier.scatter_many_from_host(list(zip(slabs, [page_ids])))

    d2h_times, h2d_times = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        slabs = copier.gather_many_to_host([page_ids])
        d2h_times.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        copier.scatter_many_from_host(list(zip(slabs, [page_ids])))
        h2d_times.append(time.perf_counter() - t0)

    return {
        "platform": jax.devices()[0].platform,
        "pinned_host_active": copier.pinned_host_active,
        "slab_mb": round(nbytes / 2**20, 2),
        "d2h_gbps": round(nbytes / min(d2h_times) / 1e9, 3),
        "h2d_gbps": round(nbytes / min(h2d_times) / 1e9, 3),
    }


def _wait(engine, job_id, timeout=60.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        for jid, status in engine.poll_finished():
            if jid == job_id:
                return status
        time.sleep(0.0005)
    raise TimeoutError("job did not finish")


def bench_fileio(iters: int, direct_io: bool) -> dict:
    from llmd_kv_cache_tpu.offload.native import STATUS_OK, NativeIOEngine

    nbytes = 64 << 20
    data = np.random.default_rng(0).integers(0, 255, nbytes, dtype=np.uint8)
    out = np.zeros_like(data)
    with tempfile.TemporaryDirectory() as root:
        engine = NativeIOEngine(num_threads=4, staging_bytes=8 << 20,
                                direct_io=direct_io)
        try:
            write_times, read_times = [], []
            for i in range(iters):
                path = os.path.join(root, f"blk{i}.bin")
                t0 = time.perf_counter()
                job = engine.begin_job()
                assert engine.submit_write(job, path, path + ".tmp", data,
                                           skip_if_exists=False)
                engine.seal_job(job)
                assert _wait(engine, job) == STATUS_OK
                write_times.append(time.perf_counter() - t0)

                t0 = time.perf_counter()
                job = engine.begin_job()
                engine.submit_read(job, path, out)
                engine.seal_job(job)
                assert _wait(engine, job) == STATUS_OK
                read_times.append(time.perf_counter() - t0)
            np.testing.assert_array_equal(out, data)
            return {
                "file_mb": nbytes >> 20,
                "numa_node": engine.numa_node(),
                "pinned_staging_workers": engine.pinned_staging_workers(),
                "write_gbps": round(nbytes / min(write_times) / 1e9, 3),
                "read_gbps": round(nbytes / min(read_times) / 1e9, 3),
            }
        finally:
            engine.close()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--pages", type=int, default=64)
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--skip-copier", action="store_true",
                        help="file I/O only (no jax import)")
    args = parser.parse_args()

    result = {}
    if not args.skip_copier:
        result["copier"] = bench_copier(args.pages, args.iters)
    result["fileio_buffered"] = bench_fileio(args.iters, direct_io=False)
    result["fileio_direct"] = bench_fileio(args.iters, direct_io=True)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
