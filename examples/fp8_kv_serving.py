#!/usr/bin/env python
"""fp8 (e4m3) KV-cache serving: half the KV bytes, same engine seams.

Serves the same prompts through a bf16-cache and an fp8-cache engine
(`EngineConfig.kv_cache_dtype="f8_e4m3"`) sharing one parameter tree,
then prints the pool byte accounting and the token agreement. On a TPU
the fp8 engine's decode rides the merged flash kernel's quantized arm
(flat whole-page 1-byte DMAs) — the measured lever for the
attention-bandwidth-bound long-context shapes (benchmarking/r5-tpu);
on CPU this demo exercises the identical code paths via XLA attention.

Usage:
  PYTHONPATH=. python examples/fp8_kv_serving.py
"""

from __future__ import annotations

import numpy as np

import jax

from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig, init_params


def cache_bytes(eng) -> int:
    total = eng.k_cache.size * eng.k_cache.dtype.itemsize
    total += eng.v_cache.size * eng.v_cache.dtype.itemsize
    return total


def main() -> None:
    cfg = LlamaConfig(vocab_size=4096, hidden_size=256, num_layers=4,
                      num_heads=8, num_kv_heads=4, head_dim=128,
                      intermediate_size=704, page_size=16)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 4000, 96).tolist() for _ in range(2)]

    engines = {}
    for dtype in ("bf16", "f8_e4m3"):
        engines[dtype] = MiniEngine(
            EngineConfig(model=cfg, num_pages=128, max_pages_per_seq=16,
                         model_name="fp8-demo", pod_identifier=f"pod-{dtype}",
                         kv_cache_dtype=dtype, decode_burst=8),
            params=params, seed=0)

    outs = {}
    for dtype, eng in engines.items():
        outs[dtype] = [eng.generate(f"r{i}", p, max_new_tokens=16)
                       for i, p in enumerate(prompts)]
        print(f"{dtype:>8s}: pool {cache_bytes(eng) / 1e6:6.2f} MB "
              f"({eng.k_cache.dtype})")

    agree = sum(
        a == b for pa, pb in zip(outs["bf16"], outs["f8_e4m3"])
        for a, b in zip(pa, pb))
    total = sum(len(p) for p in outs["bf16"])
    ratio = cache_bytes(engines["bf16"]) / cache_bytes(engines["f8_e4m3"])
    print(f"KV pool bytes: {ratio:.1f}x smaller under fp8")
    print(f"greedy tokens agree {agree}/{total} "
          f"(fp8 quantization may legitimately flip near-tie logits)")
    assert ratio > 1.9
    print("OK")


if __name__ == "__main__":
    main()
