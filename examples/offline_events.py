#!/usr/bin/env python
"""Offline KV-events demo: dummy publisher → pool → index → pod scores.

TPU-native counterpart of the reference's ``examples/kv_events/offline``
(dummy ZMQ publisher feeding the indexer with no engine involved). Runs
entirely in-process over tcp loopback and prints the scores a scheduler
would see.

Usage: PYTHONPATH=. python examples/offline_events.py
"""

import time

from llmd_kv_cache_tpu.core import TokenProcessorConfig
from llmd_kv_cache_tpu.events import BlockStoredEvent, Pool, PoolConfig, ZMQSubscriber
from llmd_kv_cache_tpu.events.publisher import KVEventPublisher
from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig

ENDPOINT = "tcp://127.0.0.1:5557"
MODEL = "meta-llama/Llama-3.1-8B-Instruct"
BLOCK_SIZE = 16


def main() -> None:
    indexer = Indexer(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size_tokens=BLOCK_SIZE, hash_seed="42"
            )
        )
    )
    pool = Pool(
        PoolConfig(concurrency=4),
        indexer.kv_block_index,
        indexer.token_processor,
    )
    pool.start()

    # Centralized delivery: the indexer binds, engines connect.
    sub = ZMQSubscriber(ENDPOINT, "kv@", pool.add_task, bind=True)
    sub.start()
    time.sleep(0.2)

    # Two fake vLLM-TPU pods with a shared 64-token system prefix; pod-a has
    # also cached a 32-token continuation.
    prefix = list(range(1000, 1064))
    continuation = list(range(2000, 2032))

    pub_a = KVEventPublisher(ENDPOINT, "vllm-tpu-pod-a", MODEL, bind=False)
    pub_b = KVEventPublisher(ENDPOINT, "vllm-tpu-pod-b", MODEL, bind=False)
    time.sleep(0.3)  # PUB slow-joiner settle

    pub_a.publish([
        BlockStoredEvent(block_hashes=[1, 2, 3, 4], tokens=prefix,
                         parent_hash=0, block_size=BLOCK_SIZE),
    ])
    pub_a.publish([
        BlockStoredEvent(block_hashes=[5, 6], tokens=continuation,
                         parent_hash=4, block_size=BLOCK_SIZE),
    ])
    pub_b.publish([
        BlockStoredEvent(block_hashes=[1, 2, 3, 4], tokens=prefix,
                         parent_hash=0, block_size=BLOCK_SIZE),
    ])

    time.sleep(0.5)
    pool.join()

    full_prompt = prefix + continuation
    scores = indexer.score_tokens(full_prompt, MODEL)
    print(f"prompt: {len(full_prompt)} tokens "
          f"({len(full_prompt) // BLOCK_SIZE} blocks)")
    print("pod scores (tier-weighted consecutive prefix blocks):")
    for pod_name, score in sorted(scores.items(), key=lambda kv: -kv[1]):
        print(f"  {pod_name}: {score}")

    expected = {"vllm-tpu-pod-a": 6.0, "vllm-tpu-pod-b": 4.0}
    assert scores == expected, f"unexpected scores: {scores} != {expected}"
    print("OK: scheduler would route to vllm-tpu-pod-a")

    sub.stop()
    pool.shutdown()
    pub_a.close()
    pub_b.close()


if __name__ == "__main__":
    main()
