#!/usr/bin/env python
"""Deployment entry point: fleet telemetry collector.

One collector per fleet. It polls every pod's admin endpoint
(``/debug/spans?since=seq`` + ``/metrics``), assembles cross-process
traces with critical-path attribution, rolls fleet percentiles up per
role, and tracks multi-window SLO burn rates. Its own admin endpoint
serves the results:

- ``/debug/traces`` — retained traces (tail-sampled) with critical paths
- ``/debug/rollup`` — fleet TTFT/ITL/score-latency percentiles per role
- ``/debug/slo``    — burn rates, thresholds, alert state per SLO
- ``/debug/anomaly``  — robust-z anomaly sentinel state per SLI series
- ``/debug/incident`` — incident black-box state (recent bundles, clock
  offsets); ``POST /debug/incident/open`` pulls a capture manually
- ``/metrics``      — the ``kvtpu_fleet_*`` / ``kvtpu_slo_*`` families

With ``--incident-dir`` set, every alert/anomaly fire edge snapshots
fleet-wide evidence (flight-recorder rings, spans, profiler windows,
membership, controller journal) into one CRC-sealed bundle there;
``hack/kvdiag.py --incident <bundle>`` replays the triage story offline.

Targets come from ``--targets`` (``name=host:port[:role]`` items) or a
JSON config file (``--config``, the ``fleetTelemetry.collector`` block,
camelCase). ``hack/kvdiag.py --port <admin-port> --fleet`` snapshots the
whole surface.

Usage:
  python examples/telemetry_collector_main.py \
      --targets shard-0=127.0.0.1:9400:indexer-shard,pod-0=127.0.0.1:9401:decode \
      --admin-port 9500 [--scrape-interval-s 5]
  python examples/telemetry_collector_main.py --config collector.json
"""

import argparse
import json
import signal
import threading

from llmd_kv_cache_tpu.services.telemetry_collector import (
    CollectorConfig,
    ScrapeTarget,
    TelemetryCollector,
)
from llmd_kv_cache_tpu.telemetry.incident import IncidentConfig
from llmd_kv_cache_tpu.utils.logging import configure_from_env


def parse_target(spec: str) -> ScrapeTarget:
    """``name=host:port[:role]`` (name optional: ``host:port[:role]``)."""
    name, eq, rest = spec.partition("=")
    if not eq:
        name, rest = "", spec
    parts = rest.split(":")
    if len(parts) == 3:
        address, role = f"{parts[0]}:{parts[1]}", parts[2]
    else:
        address, role = rest, ""
    return ScrapeTarget(name=name or address, address=address, role=role)


def main() -> None:
    configure_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--targets", default="",
                        help="comma-separated name=host:port[:role] items")
    parser.add_argument("--config", default=None,
                        help="JSON file with the fleetTelemetry.collector "
                             "block (camelCase; overrides other flags)")
    parser.add_argument("--admin-port", type=int, default=9500)
    parser.add_argument("--admin-host", default="127.0.0.1")
    parser.add_argument("--scrape-interval-s", type=float, default=5.0)
    parser.add_argument("--slo-latency-threshold-s", type=float, default=2.0,
                        help="trace duration beyond which the tail sampler "
                             "always retains the trace")
    parser.add_argument("--incident-dir", default="",
                        help="directory for incident black-box bundles; "
                             "unset disables alert-triggered capture")
    parser.add_argument("--incident-max", type=int, default=16,
                        help="keep-N retention over bundle files in "
                             "--incident-dir (oldest deleted first)")
    args = parser.parse_args()

    if args.config:
        with open(args.config, encoding="utf-8") as f:
            cfg = CollectorConfig.from_dict(json.load(f))
    else:
        specs = [t.strip() for t in args.targets.split(",") if t.strip()]
        if not specs:
            parser.error("either --targets or --config is required")
        cfg = CollectorConfig(
            targets=tuple(parse_target(s) for s in specs),
            scrape_interval_s=args.scrape_interval_s,
            admin_port=args.admin_port,
            host=args.admin_host,
            slo_latency_threshold_s=args.slo_latency_threshold_s,
            incident=IncidentConfig(
                directory=args.incident_dir,
                max_bundles=args.incident_max,
            ),
        )

    collector = TelemetryCollector(cfg)
    collector.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        collector.stop()


if __name__ == "__main__":
    main()
