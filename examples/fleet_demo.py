#!/usr/bin/env python
"""Fleet demo: every subsystem in one run.

Three engine pods with shared storage, KV events over real ZMQ into the
indexer, KV-aware routing with speculative convergence, storage-tier
restore on a cold pod, and the evictor keeping the store under budget —
the whole framework end to end in one process.

Usage: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/fleet_demo.py
"""

import os
import shutil
import tempfile
import time

from llmd_kv_cache_tpu.core import TokenProcessorConfig
from llmd_kv_cache_tpu.events import Pool, PoolConfig, ZMQSubscriber
from llmd_kv_cache_tpu.events.publisher import KVEventPublisher
from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig
from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec
from llmd_kv_cache_tpu.evictor import Evictor, EvictorConfig
from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig
from llmd_kv_cache_tpu.scoring.router import KVAwareRouter

ENDPOINT = "tcp://127.0.0.1:15990"
MODEL = "fleet-demo"


def main() -> None:
    store = tempfile.mkdtemp(prefix="kvtpu-fleet-")
    tiny = LlamaConfig.tiny()

    # Indexer side: centralized subscriber + sharded pool.
    indexer = Indexer(IndexerConfig(
        token_processor_config=TokenProcessorConfig(block_size_tokens=tiny.page_size)
    ))
    pool = Pool(PoolConfig(concurrency=2), indexer.kv_block_index,
                indexer.token_processor)
    pool.start()
    sub = ZMQSubscriber(ENDPOINT, "kv@", pool.add_task, bind=True)
    sub.start()
    time.sleep(0.2)

    # Three pods sharing one offload store, publishing real events.
    spec = SharedStorageOffloadSpec(
        root=store, model_name=MODEL, page_size=tiny.page_size,
        num_layers=tiny.num_layers, kv_heads=tiny.num_kv_heads,
        head_dim=tiny.head_dim, parallel_agnostic=True,
        events_endpoint=ENDPOINT,
    )
    pods = {}
    pubs = {}
    for name in ("pod-0", "pod-1", "pod-2"):
        pub = KVEventPublisher(ENDPOINT, name, MODEL, bind=False)
        pubs[name] = pub

        def sink(events, pub=pub):
            pub.publish(events)

        pods[name] = MiniEngine(
            EngineConfig(model=tiny, num_pages=96, max_pages_per_seq=16,
                         model_name=MODEL, pod_identifier=name),
            event_sink=sink,
            offload_spec=spec,
        )
    time.sleep(0.3)  # PUB slow-joiner settle

    router = KVAwareRouter(indexer, list(pods))

    system_prompt = list(range(1000, 1032))  # 8 shared blocks

    print("=== phase 1: routed traffic (speculative + confirmed residency)")
    for i in range(6):
        prompt = system_prompt + [2000 + i * 7, 2001 + i * 7, 2002 + i, 2003]
        pod = router.route(prompt, MODEL)
        req = pods[pod].add_request(f"r{i}", prompt, max_new_tokens=2)
        while not req.done:
            pods[pod].step()
        print(f"  request {i} → {pod} (prefix cached: {req.cached_len} tokens)")

    time.sleep(0.5)
    scores = indexer.score_tokens(system_prompt, MODEL)
    print(f"  confirmed residency scores: {scores}")

    print("=== phase 2: cold pod restores the shared prefix from storage")
    for p in pods.values():
        p.flush_offload()
    cold = MiniEngine(
        EngineConfig(model=tiny, num_pages=96, max_pages_per_seq=16,
                     model_name=MODEL, pod_identifier="pod-cold"),
        offload_spec=spec,
    )
    req = cold.add_request("cold", system_prompt + [42, 43, 44, 45],
                           max_new_tokens=2)
    print(f"  pod-cold admission: {req.cached_len} tokens restored from storage")

    print("=== phase 3: evictor reclaims the store")
    n_files = sum(len(fs) for _, _, fs in os.walk(store))
    ev = Evictor(
        EvictorConfig(store_root=store, num_crawlers=1, min_idle_seconds=0,
                      storage_events_endpoint=ENDPOINT, model_name=MODEL),
        usage_fn=lambda: 0.95,
    )
    time.sleep(0.3)
    ev.activator_pass()
    deleted = ev.crawl_and_delete_pass(0, max_batches=10)
    print(f"  store had {n_files} files; evictor deleted {deleted}, "
          f"BlockRemoved events published")
    time.sleep(0.5)
    pool.join()

    print("=== done")
    sub.stop()
    pool.shutdown()
    for pub in pubs.values():
        pub.close()
    shutil.rmtree(store, ignore_errors=True)


if __name__ == "__main__":
    main()
