#!/usr/bin/env python
"""Serve a local HuggingFace checkpoint through the paged engine.

The user-facing entry for real weights: point it at a checkpoint
directory (Llama/Mistral/Mixtral/Qwen2/Qwen3/Qwen3-MoE/DeepSeek — every
family logits-parity-pinned to transformers in tests/test_hf_loader.py),
it converts to the TPU-native parameter tree, admits the prompt through
the content-addressed prefix cache, and streams greedy tokens from the
continuous-batching scheduler.

Usage:
  PYTHONPATH=. python examples/serve_hf_checkpoint.py /path/to/ckpt \\
      --prompt "The capital of France is" --max-new-tokens 32

With no checkpoint argument, the demo builds a tiny random-init Qwen3 in
a temp dir first (no downloads; zero-egress-safe) and serves that — the
full disk path (save_pretrained → safetensors → conversion) still runs.
"""

from __future__ import annotations

import argparse
import contextlib
import sys
import tempfile


def _demo_checkpoint(tmp: str) -> str:
    """Build a tiny random-init Qwen3 checkpoint on disk (no network)."""
    import torch
    from transformers import AutoTokenizer  # noqa: F401 (env check)
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(0)
    cfg = Qwen3Config(
        vocab_size=4096, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, tie_word_embeddings=True)
    Qwen3ForCausalLM(cfg).save_pretrained(tmp)
    return tmp


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("checkpoint", nargs="?", default=None,
                    help="HF checkpoint directory (local; no downloads)")
    ap.add_argument("--prompt", default="The capital of France is")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=2048)
    args = ap.parse_args()

    from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
    from llmd_kv_cache_tpu.models.hf_loader import load_hf_checkpoint

    demo_ids = None
    cleanup = contextlib.ExitStack()
    if args.checkpoint is None:
        tmpdir = cleanup.enter_context(
            tempfile.TemporaryDirectory(prefix="hf-demo-"))
        print("no checkpoint given: building a tiny random-init Qwen3 demo",
              file=sys.stderr)
        args.checkpoint = _demo_checkpoint(tmpdir)
        demo_ids = list(range(30, 46))  # random-init: tokenizer-free demo

    print(f"converting {args.checkpoint} …", file=sys.stderr)
    with cleanup:
        cfg, params = load_hf_checkpoint(args.checkpoint,
                                         page_size=args.page_size)
    import jax

    # Tied checkpoints alias lm_head to the embedding — count it once.
    n_params = sum(p.size for p in jax.tree.leaves(params))
    if params["lm_head"].shape == params["embed"].T.shape and bool(
            (params["lm_head"] == params["embed"].T).all()):
        n_params -= params["lm_head"].size
    print(f"model: {cfg.num_layers}L/{cfg.hidden_size}h "
          f"{n_params / 1e6:.1f}M params, families: "
          f"mla={cfg.is_mla} moe={cfg.num_experts > 0} "
          f"qk_norm={cfg.qk_norm} window={cfg.sliding_window}",
          file=sys.stderr)

    if demo_ids is not None:
        prompt_ids = demo_ids
        decode = lambda ids: str(ids)  # noqa: E731
    else:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.checkpoint)
        prompt_ids = tok(args.prompt)["input_ids"]
        decode = tok.decode

    max_pages = (len(prompt_ids) + args.max_new_tokens
                 ) // cfg.page_size + 3
    eng = MiniEngine(
        EngineConfig(model=cfg, num_pages=args.num_pages,
                     max_pages_per_seq=max_pages, model_name="hf-serve",
                     pod_identifier="pod-0"),
        params=params)
    req = eng.enqueue("r0", prompt_ids, max_new_tokens=args.max_new_tokens)
    while not req.done:
        eng.step()
    print(decode(list(req.output)))


if __name__ == "__main__":
    main()
