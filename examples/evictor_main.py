#!/usr/bin/env python
"""Deployment entry point: storage-lifecycle evictor (env-var configured)."""

import signal
import threading

from llmd_kv_cache_tpu.evictor import Evictor, EvictorConfig
from llmd_kv_cache_tpu.utils.logging import configure_from_env


def main() -> None:
    configure_from_env()
    evictor = Evictor(EvictorConfig.from_env())
    evictor.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    evictor.stop()


if __name__ == "__main__":
    main()
