#!/usr/bin/env python
"""Long-context serving with sequence-parallel prefill.

A long prompt's prefill is the serving cost that scales quadratically
with context; with an ``sp`` axis on the engine mesh, each chunk's tokens
place sharded on the sequence dim and XLA splits the per-token compute
across sp devices (collectives derived from the shardings) — the serving
analog of the training-side ring attention, composed here with tp
(Megatron params) on one mesh. Tokens must be identical to the
single-device engine; prefix-cache resume (nonzero ctx into the sharded
chunk) works unchanged.

Usage:
  PYTHONPATH=. JAX_PLATFORMS=cpu \\
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/long_context_sp.py
"""

import numpy as np

import jax

from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig, init_params
from llmd_kv_cache_tpu.parallel.mesh import make_mesh

MODEL = "sp-demo"


def engine(cfg, params, mesh=None):
    return MiniEngine(
        EngineConfig(model=cfg, num_pages=192, max_pages_per_seq=96,
                     model_name=MODEL, pod_identifier="pod-0",
                     max_prefill_tokens=64),  # chunked long-prompt prefill
        params=params, mesh=mesh,
    )


def main() -> None:
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=4,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    long_prompt = rng.integers(1, 500, 256).tolist()  # 4 sp-sharded chunks

    print(f"devices: {len(jax.devices())} × {jax.devices()[0].platform}")

    ref = engine(cfg, params).generate("r", long_prompt, max_new_tokens=8)
    mesh = make_mesh({"tp": 2, "sp": 2}, jax.devices()[:4])
    sp = engine(cfg, params, mesh=mesh)
    out = sp.generate("r", long_prompt, max_new_tokens=8)
    print(f"single-device tokens: {ref}")
    print(f"tp=2 × sp=2 tokens:   {out}")
    assert out == ref

    # Prefix-cache resume: the shared 256-token prefix is already paged in,
    # so only the 16-token suffix prefills (one sharded chunk).
    ext = long_prompt + rng.integers(1, 500, 16).tolist()
    ref2 = engine(cfg, params).generate("r2", ext, max_new_tokens=4)
    req = sp.add_request("r2", ext, max_new_tokens=4)  # prefill now
    cached = req.cached_len
    while not req.done:  # decode through the scheduler
        sp.step()
    print(f"resume: cached {cached}/{len(ext)} tokens, "
          f"tokens {req.output} == {ref2}")
    assert req.output == ref2 and cached >= 250

    print("OK: sp prefill serves long contexts token-identically")


if __name__ == "__main__":
    main()
