#!/usr/bin/env python
"""Sharded indexer control plane demo: 4 shard replicas, scatter-gather
scoring, replica failover, and anti-entropy rejoin — all in one process
over localhost gRPC.

Walks the full cluster/ story end to end:

1. Four ``IndexerService`` replicas come up, each with a shard identity
   (``clusterConfig.shardId``). Every replica ingests the same broadcast
   event stream; its ``ShardFilterIndex`` keeps only the block keys the
   consistent-hash ring assigns it (replication factor 2).
2. A ``ShardRouter`` scores prompts by fanning ``LookupBlocks`` out to
   the owning shards and merging the hits through the ordinary
   longest-prefix scorer.
3. One shard is killed. Scoring continues without interruption: the
   breaker opens, the dead shard's keys fail over to their replica
   owners, and scores stay exact.
4. The shard comes back from its snapshot and repairs the events it
   missed via one peer anti-entropy round.

Usage: PYTHONPATH=. python examples/sharded_cluster_demo.py
"""

import shutil
import tempfile
import time

from llmd_kv_cache_tpu.cluster import ShardRouter
from llmd_kv_cache_tpu.cluster.config import ClusterConfig
from llmd_kv_cache_tpu.core import TokenProcessorConfig
from llmd_kv_cache_tpu.events import PoolConfig
from llmd_kv_cache_tpu.events.model import BlockStoredEvent, EventBatch
from llmd_kv_cache_tpu.recovery import RecoveryConfig
from llmd_kv_cache_tpu.scoring.indexer import IndexerConfig
from llmd_kv_cache_tpu.services.indexer_service import IndexerService, serve

MODEL = "meta-llama/Llama-3.1-8B-Instruct"
BLOCK_SIZE = 16
ADDRS = [f"127.0.0.1:{p}" for p in range(15950, 15954)]


def make_service(addr: str, snap_root: str) -> tuple[IndexerService, object]:
    cfg = IndexerConfig(
        token_processor_config=TokenProcessorConfig(
            block_size_tokens=BLOCK_SIZE),
        recovery_config=RecoveryConfig(
            snapshot_dir=f"{snap_root}/{addr.replace(':', '_')}",
            snapshot_interval_s=0.0,
            warmup_staleness_bound_s=1e9,
        ),
        cluster_config=ClusterConfig(
            shard_addresses=ADDRS,
            shard_id=addr,
            replication_factor=2,
            breaker_reset_timeout_s=0.5,
        ),
    )
    svc = IndexerService(cfg, PoolConfig(concurrency=1))
    svc.start()
    return svc, serve(addr, svc)


def broadcast(services, pod: str, tokens: list, engine_base: int) -> None:
    """The full event stream every replica sees; each keeps what it owns."""
    n = len(tokens) // BLOCK_SIZE
    batch = EventBatch(
        timestamp=time.time(),
        events=[BlockStoredEvent(
            block_hashes=list(range(engine_base, engine_base + n)),
            tokens=list(tokens), parent_hash=0, block_size=BLOCK_SIZE,
            device_tier="gpu",
        )],
    )
    for svc in services:
        svc.pool.process_event_batch(batch, pod, MODEL)


def main() -> None:
    snap_root = tempfile.mkdtemp(prefix="kvtpu-shard-demo-")
    services, servers = {}, {}
    router = None
    try:
        for addr in ADDRS:
            services[addr], servers[addr] = make_service(addr, snap_root)
        print(f"4 shard replicas up: {', '.join(ADDRS)}")

        prompt = list(range(1, 1 + 32 * BLOCK_SIZE))  # 32 blocks
        broadcast(services.values(), "pod-a", prompt, 1000)
        broadcast(services.values(), "pod-b", prompt[:16 * BLOCK_SIZE], 2000)
        for addr, svc in services.items():
            view = svc.shard_index.debug_view()
            print(f"  {addr}: owned={view['owned_writes']} "
                  f"filtered={view['filtered_writes']}")

        router = ShardRouter(
            ClusterConfig(shard_addresses=ADDRS, replication_factor=2,
                          breaker_reset_timeout_s=0.5),
            token_processor_config=TokenProcessorConfig(
                block_size_tokens=BLOCK_SIZE),
        )
        res = router.score(prompt, MODEL)
        print(f"scatter-gather scores: {res.scores} "
              f"({res.rpcs} RPCs, degraded={res.degraded_shards})")

        keys = router.token_processor.tokens_to_kv_block_keys(0, prompt, MODEL)
        victim = router.ring.owner(keys[0])
        services[victim].recovery.snapshot_now(reason="demo")
        servers[victim].stop(grace=0)
        services[victim].stop()
        print(f"killed {victim} (primary owner of block 0)")

        res = router.score(prompt, MODEL)
        assert res.scores and not res.degraded_shards
        print(f"failover scores (exact, via replica owners): {res.scores}")

        # Events the dead shard misses while down.
        survivors = [s for a, s in services.items() if a != victim]
        prompt2 = list(range(5001, 5001 + 32 * BLOCK_SIZE))
        broadcast(survivors, "pod-c", prompt2, 3000)

        svc2, server2 = make_service(victim, snap_root)
        services[victim], servers[victim] = svc2, server2
        svc2.attach_peer_digest_source()
        stats = svc2.reconcile_now()
        print(f"{victim} rejoined: snapshot bootstrap + anti-entropy "
              f"repaired {stats['repaired_added']} blocks")

        res = router.score(prompt2, MODEL)
        print(f"post-rejoin scores: {res.scores}")
        print("OK")
    finally:
        if router is not None:
            router.close()
        for server in servers.values():
            server.stop(grace=0)
        for svc in services.values():
            try:
                svc.stop()
            except Exception:
                pass  # the victim's first incarnation is already stopped
        shutil.rmtree(snap_root, ignore_errors=True)


if __name__ == "__main__":
    main()
