#!/usr/bin/env python
"""Deployment entry point: the self-driving fleet controller.

One controller per fleet. It closes the loop the observability planes
opened: SLO burn-rate edges (telemetry collector's ``/debug/slo``),
critical-path attribution (``/debug/traces``), the what-if capacity
table (``/debug/workingset``), and each engine pod's role + handoff
starvation stats (``/debug/role``) flow in; indexer shard join/leave,
prefill↔decode re-roles, and pre-scale-down drains flow out through the
pods' guarded admin POST endpoints.

Safety rails (see docs/architecture.md "Fleet controller"):
hysteresis bands + confirm rounds + per-action cooldowns + a global
action budget mean the controller never flaps; every action is
journaled (``--journal``) before and after execution so a restarted
controller resumes without repeating or reversing in-flight actions;
``--dry-run`` records would-have-acted decisions without touching the
cluster. ``kvdiag --fleet`` (pointed at ``--admin-port``) shows the
action history with each action's causing signal.

Usage:
  python examples/fleet_controller_main.py \
      --collector 127.0.0.1:9500 \
      --pods pod-0=127.0.0.1:9401,pod-1=127.0.0.1:9402 \
      --admin-port 9600 --journal /var/run/kvtpu/controller.journal \
      [--interval-s 5] [--dry-run]
  python examples/fleet_controller_main.py --config controller.json
"""

import argparse
import json
import signal
import threading

from llmd_kv_cache_tpu.services.fleet_controller import (
    FleetControllerService,
    FleetControllerServiceConfig,
)
from llmd_kv_cache_tpu.utils.logging import configure_from_env


def parse_pods(spec: str) -> dict:
    """``pod-id=host:port`` comma-separated items."""
    out = {}
    for item in filter(None, (s.strip() for s in spec.split(","))):
        name, eq, address = item.partition("=")
        if not eq:
            raise ValueError(f"bad --pods item {item!r} (want id=host:port)")
        out[name] = address
    return out


def main() -> None:
    configure_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--collector", default="",
                        help="host:port of the telemetry collector's admin "
                             "endpoint")
    parser.add_argument("--pods", default="",
                        help="comma-separated pod-id=host:port admin "
                             "addresses of the engine pods")
    parser.add_argument("--admin-port", type=int, default=0,
                        help="this controller's own admin endpoint "
                             "(/debug/controller); 0 = off")
    parser.add_argument("--journal", default="",
                        help="append-only action journal path (warm-restart "
                             "safety); empty = no persistence")
    parser.add_argument("--interval-s", type=float, default=5.0,
                        help="reconcile loop interval (default 5s)")
    parser.add_argument("--dry-run", action="store_true",
                        help="record would-have-acted decisions without "
                             "mutating the cluster")
    parser.add_argument("--config", default=None,
                        help="JSON file with the controllerConfig block "
                             "(camelCase; overrides other flags)")
    args = parser.parse_args()

    if args.config:
        with open(args.config) as f:
            cfg = FleetControllerServiceConfig.from_dict(json.load(f))
    else:
        controller = {
            "loopIntervalS": args.interval_s,
            "dryRun": args.dry_run,
            "journalPath": args.journal,
        }
        cfg = FleetControllerServiceConfig.from_dict({
            "collectorAddress": args.collector,
            "podAdmin": parse_pods(args.pods),
            "adminPort": args.admin_port,
            "controllerConfig": controller,
        })

    service = FleetControllerService(cfg)
    service.start()
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        service.stop()


if __name__ == "__main__":
    main()
