#!/usr/bin/env python
"""Deployment entry point: one engine pod of the chart's topology.

Runs a MiniEngine whose KV events ride a ZMQ PUB socket to the indexer
service (``deploy/chart`` wires the same triangle: indexer + engine pods +
evictor over a shared store). Work arrives through a file-based control
directory so the pod is drivable from shell scripts and the multi-process
cluster test (tests/test_cluster_e2e.py) without an HTTP stack:

    <control>/<name>.req.json   {"request_id": "...", "prompt": [ints],
                                 "max_new_tokens": N}
    <control>/<name>.out.json   {"request_id": "...", "output": [ints]}

The pod writes ``<control>/<pod-id>.ready`` once serving. SIGTERM exits.

``--admin-port`` (off by default; ``auto`` = ephemeral) starts the stdlib
admin endpoint with the engine-telemetry debug section (``/metrics``,
``/debug/vars`` → ``engine``, and — when ``--profile-dir`` is set —
``/debug/profile?duration_s=N``). The bound port is written to
``<control>/<pod-id>.admin_port`` so tests and ``hack/kvdiag.py`` can find
it.

Usage:
  python examples/engine_pod_main.py --pod-id pod-0 \
      --zmq-endpoint tcp://127.0.0.1:5557 --control-dir /tmp/ctl \
      [--offload-root /mnt/kv-store] [--model-name tiny] \
      [--admin-port auto] [--profile-dir /tmp/xplane]
"""

import argparse
import json
import os
import pathlib
import signal
import time

from llmd_kv_cache_tpu.events.publisher import KVEventPublisher
from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig
from llmd_kv_cache_tpu.offload.spec import SharedStorageOffloadSpec
from llmd_kv_cache_tpu.services.admin import AdminServer
from llmd_kv_cache_tpu.telemetry import EngineTelemetryConfig
from llmd_kv_cache_tpu.utils.logging import configure_from_env


def main() -> None:
    configure_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--pod-id", required=True)
    parser.add_argument("--zmq-endpoint", required=True)
    parser.add_argument("--control-dir", required=True)
    parser.add_argument("--model-name", default="tiny")
    parser.add_argument("--offload-root", default=None)
    parser.add_argument("--role", default="both",
                        choices=["both", "prefill", "decode"],
                        help="disaggregated serving role: 'prefill' pods "
                             "commit each chunk's KV to the shared store "
                             "and stop at first token; 'decode' pods pull "
                             "transferred prefixes via the restore path. "
                             "Non-default roles require --offload-root.")
    parser.add_argument("--admin-port", default="0",
                        help='admin/metrics endpoint: "0" = off (default), '
                             '"auto" = ephemeral port, else a port number')
    parser.add_argument("--profile-dir", default="",
                        help="enable /debug/profile, writing jax.profiler "
                             "xplane captures here")
    parser.add_argument("--span-export", action="store_true",
                        help="fleet telemetry: record finished spans "
                             "(process identity = the pod id) into a ring "
                             "served at /debug/spans on --admin-port for "
                             "the telemetry collector to pull")
    parser.add_argument("--pyprof", action="store_true",
                        help="continuous profiling: always-on sampling "
                             "profiler serving folded stacks at "
                             "/debug/pyprof (+ /debug/pyprof/capture) on "
                             "--admin-port")
    parser.add_argument("--pyprof-hz", type=float, default=67.0,
                        help="sampling rate for --pyprof (default 67)")
    parser.add_argument("--pyprof-window-s", type=float, default=10.0,
                        help="profile window length for --pyprof "
                             "(default 10s)")
    parser.add_argument("--workingset", action="store_true",
                        help="working-set analytics: sample block reuse "
                             "on admission/eviction/offload and serve "
                             "reuse windows at /debug/workingset on "
                             "--admin-port for the collector's what-if "
                             "capacity table")
    parser.add_argument("--workingset-sample-rate", type=float, default=0.05,
                        help="spatial sampling rate for --workingset "
                             "(default 0.05)")
    parser.add_argument("--workingset-window-s", type=float, default=10.0,
                        help="window length for --workingset (default 10s)")
    parser.add_argument("--audit", action="store_true",
                        help="ground-truth audit: record every request's "
                             "realized prefix outcome (HBM hit vs restored "
                             "vs recomputed blocks) in a ring served at "
                             "/debug/audit on --admin-port for the "
                             "collector's score-vs-reality join; requests "
                             "may carry the prediction they were routed on "
                             "via a 'feedback' object in the req.json")
    parser.add_argument("--audit-max-records", type=int, default=2048,
                        help="audit ring depth for --audit (default 2048)")
    args = parser.parse_args()

    cfg = LlamaConfig.tiny()
    publisher = KVEventPublisher(
        args.zmq_endpoint, pod_identifier=args.pod_id,
        model_name=args.model_name, bind=False,
    )
    spec = None
    if args.offload_root:
        spec = SharedStorageOffloadSpec(
            root=args.offload_root, model_name=args.model_name,
            page_size=cfg.page_size, num_layers=cfg.num_layers,
            kv_heads=cfg.num_kv_heads, head_dim=cfg.head_dim,
            io_threads=2, parallel_agnostic=True,
        )
    if args.role != "both" and spec is None:
        parser.error(f"--role {args.role} requires --offload-root (the "
                     "handoff moves KV through the shared store)")
    engine = MiniEngine(
        EngineConfig(
            model=cfg, num_pages=64, max_pages_per_seq=16,
            model_name=args.model_name, pod_identifier=args.pod_id,
            role=args.role,
            telemetry=EngineTelemetryConfig(profile_dir=args.profile_dir),
        ),
        event_sink=publisher.publish,
        offload_spec=spec,
        seed=0,  # all pods share deterministic params: cross-pod
        #         storage restores must be bit-exact resumable
    )
    handoff = None
    if args.role != "both":
        # Local coordinator: feeds the kvtpu_handoff_* metrics and, on a
        # prefill pod, streams chunk commits. Cross-pod availability rides
        # the store's own BlockStored advertisements in this file-driven
        # deployment shim.
        from llmd_kv_cache_tpu.offload.handoff import HandoffCoordinator

        handoff = HandoffCoordinator()
        engine.attach_handoff(handoff)

    control = pathlib.Path(args.control_dir)
    control.mkdir(parents=True, exist_ok=True)

    running = [True]
    signal.signal(signal.SIGTERM, lambda *_: running.__setitem__(0, False))

    admin = None
    if args.admin_port != "0":
        port = 0 if args.admin_port == "auto" else int(args.admin_port)
        admin = AdminServer(port=port, expose_debug=True)
        if engine.telemetry is not None:
            engine.telemetry.attach_admin(admin)
        if args.span_export:
            from llmd_kv_cache_tpu.telemetry import (
                FleetTelemetryConfig,
                enable_span_export,
            )

            source = enable_span_export(
                FleetTelemetryConfig(span_export=True),
                default_identity=args.pod_id)
            if source is not None:
                admin.register_spans_source(source)
        if args.pyprof:
            from llmd_kv_cache_tpu.telemetry import (
                FleetTelemetryConfig,
                SamplingProfilerConfig,
                enable_pyprof,
            )

            pyprof = enable_pyprof(
                FleetTelemetryConfig(
                    pyprof=SamplingProfilerConfig(
                        enabled=True, hz=args.pyprof_hz,
                        window_s=args.pyprof_window_s)),
                default_identity=args.pod_id)
            if pyprof is not None:
                prof_source, prof_capture = pyprof
                admin.register_pyprof_source(prof_source)
                admin.register_pyprof_capture(prof_capture)
        if args.workingset:
            from llmd_kv_cache_tpu.telemetry import (
                FleetTelemetryConfig,
                WorkingSetConfig,
                enable_workingset,
            )

            tracker = enable_workingset(
                FleetTelemetryConfig(
                    workingset=WorkingSetConfig(
                        enabled=True,
                        sample_rate=args.workingset_sample_rate,
                        window_s=args.workingset_window_s)),
                default_identity=args.pod_id)
            if tracker is not None:
                engine.attach_workingset(tracker)
                admin.register_workingset_source(tracker.export_since)
        if args.audit:
            from llmd_kv_cache_tpu.telemetry.audit import AuditLog

            audit_log = AuditLog(capacity=args.audit_max_records)
            engine.attach_audit(audit_log)
            admin.register_audit_source(audit_log.export_since)
            admin.register_debug("audit_state", audit_log.debug_view)
        # Fleet-controller surface: /debug/role reports this pod's
        # serving role plus the handoff coordinator's residency/
        # starvation stats; POST /debug/role?set=<role> re-roles the
        # engine (guarded — only wired because this entry point opts in);
        # POST /debug/drain runs the PR 4 graceful drain.
        def role_view() -> dict:
            view = {"pod": args.pod_id, "role": engine.cfg.role}
            if handoff is not None:
                view["starvation"] = handoff.starvation()
            return view

        def set_role(params) -> dict:
            role = params.get("set", "")
            previous = engine.set_role(role)  # ValueError → HTTP 400
            return {"ok": True, "pod": args.pod_id, "role": role,
                    "previous": previous}

        admin.register_debug("role", role_view)
        admin.register_action("role", set_role)

        from llmd_kv_cache_tpu.recovery.drain import DrainCoordinator

        drainer = DrainCoordinator(
            intake_stoppers=[lambda: running.__setitem__(0, False)],
            offload=getattr(engine, "offload_manager", None),
        )

        def drain_action(params) -> dict:
            if "deadline_s" in params:
                drainer.deadline_s = float(params["deadline_s"])
            return drainer.drain()

        admin.register_action("drain", drain_action)
        admin.start()
        (control / f"{args.pod_id}.admin_port").write_text(str(admin.port))

    # Warm the tiny model (first jit), then declare readiness.
    engine.generate(f"{args.pod_id}-warm", [1, 2, 3, 4], max_new_tokens=1)
    (control / f"{args.pod_id}.ready").write_text("ok")

    served = set()
    while running[0]:
        for req_file in sorted(control.glob(f"{args.pod_id}.*.req.json")):
            if req_file.name in served:
                continue
            served.add(req_file.name)
            req = json.loads(req_file.read_text())
            max_new = req.get("max_new_tokens", 4)
            if args.role == "prefill":
                # Prefill pods never decode: the request ends at the
                # bootstrap token, its KV committed to the shared store.
                max_new = 1
            if "traceparent" in req or "feedback" in req:
                # Audit-plane path: carry the routing prediction (and the
                # scorer's trace) onto the realized-outcome record.
                fb = None
                fb_dict = req.get("feedback")
                if fb_dict:
                    from llmd_kv_cache_tpu.services.indexer_service import (
                        ScoreFeedback,
                    )

                    fb = ScoreFeedback(
                        traceparent=fb_dict.get("traceparent", ""),
                        chosen_pod=fb_dict.get("chosen_pod", ""),
                        predicted_blocks=float(
                            fb_dict.get("predicted_blocks", 0.0)),
                        total_blocks=int(fb_dict.get("total_blocks", 0)),
                        scores=dict(fb_dict.get("scores", {})),
                        residency=dict(fb_dict.get("residency", {})),
                        staleness_s=float(fb_dict.get("staleness_s", 0.0)),
                    )
                req_obj = engine.enqueue(
                    req["request_id"], req["prompt"],
                    max_new_tokens=max_new,
                    traceparent=req.get("traceparent"),
                    feedback=fb,
                )
                while not req_obj.done:
                    engine.step()
                out = req_obj.output
            else:
                out = engine.generate(
                    req["request_id"], req["prompt"],
                    max_new_tokens=max_new,
                )
            if spec is not None:
                engine.flush_offload()
            # Atomic publish: readers poll for the .out.json name, so it
            # must never be observable half-written.
            out_file = req_file.with_suffix("").with_suffix(".out.json")
            tmp_file = out_file.with_suffix(".tmp")
            tmp_file.write_text(json.dumps(
                {"request_id": req["request_id"], "output": out}))
            os.replace(tmp_file, out_file)
        time.sleep(0.05)

    if admin is not None:
        admin.stop()


if __name__ == "__main__":
    main()
