#!/usr/bin/env python
"""Indexer with a Redis/Valkey backend (shared persistent index).

Counterpart of the reference's ``examples/kv_cache_index/main.go``: build
an Indexer whose block index lives in Redis so multiple indexer replicas
(or restarts) share one view, add residency for a pod, score a prompt.

Backend selection is config-driven: with ``KVTPU_REDIS_URL`` set (e.g.
``redis://localhost:6379/0``) the Redis backend is used — including the
server-side Lua prune scripts; without it the example falls back to the
in-memory backend so it stays runnable headlessly (the reference example
likewise needs a reachable Redis).

Usage:
  [KVTPU_REDIS_URL=redis://localhost:6379/0] \\
  PYTHONPATH=. JAX_PLATFORMS=cpu python examples/redis_indexer.py
"""

import os

import numpy as np

from llmd_kv_cache_tpu.core import PodEntry, TokenProcessorConfig
from llmd_kv_cache_tpu.scoring import Indexer, IndexerConfig

MODEL = "redis-demo"


def main() -> None:
    url = os.environ.get("KVTPU_REDIS_URL")
    if url:
        cfg = IndexerConfig.from_dict({
            "tokenProcessorConfig": {"blockSizeTokens": 16},
            "kvBlockIndexConfig": {"redisConfig": {"address": url}},
        })
        backend = f"redis ({url})"
    else:
        cfg = IndexerConfig.from_dict({
            "tokenProcessorConfig": {"blockSizeTokens": 16},
            "kvBlockIndexConfig": {"inMemoryConfig": {}},
        })
        backend = "in-memory (set KVTPU_REDIS_URL for the Redis backend)"
    indexer = Indexer(cfg)
    print(f"index backend: {backend}")

    # An engine (pod-a) stores the first 4 blocks of a prompt: in a real
    # deployment this arrives as KV events; here we add directly.
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 30000, 96).tolist()  # 6 blocks of 16
    keys = indexer.compute_block_keys(prompt, MODEL)
    indexer.kv_block_index.add(keys[:4], keys[:4],
                               [PodEntry("vllm-tpu-pod-a", "tpu-hbm")])
    indexer.kv_block_index.add(keys[:2], keys[:2],
                               [PodEntry("vllm-tpu-pod-b", "cpu")])

    scores = indexer.score_tokens(prompt, MODEL)
    print("pod scores (tier-weighted consecutive prefix blocks):")
    for pod, score in sorted(scores.items(), key=lambda kv: -kv[1]):
        print(f"  {pod}: {score}")
    best = max(scores.items(), key=lambda kv: kv[1])[0]
    assert best == "vllm-tpu-pod-a"
    print(f"OK: scheduler would route to {best}")
    print("=== done")


if __name__ == "__main__":
    main()
