#!/usr/bin/env python
"""Deployment entry point: indexer service (event plane + scoring RPC).

Counterpart of the reference's ``examples/kv_cache_index_service``. Runs the
sharded event pool with either a centralized bound subscriber or pod
discovery, and serves ``GetPodScores`` over gRPC.

Usage:
  python examples/indexer_service_main.py \
      --zmq-endpoint tcp://0.0.0.0:5557 --grpc-address 0.0.0.0:50051 \
      --block-size 16 --hash-seed 42 [--discover-pods-file pods.json]
"""

import argparse

from llmd_kv_cache_tpu.core.token_processor import TokenProcessorConfig
from llmd_kv_cache_tpu.events.pool import PoolConfig
from llmd_kv_cache_tpu.events.reconciler import FileDiscovery, PodReconciler
from llmd_kv_cache_tpu.scoring import IndexerConfig
from llmd_kv_cache_tpu.services.indexer_service import IndexerService, serve
from llmd_kv_cache_tpu.utils.logging import configure_from_env


def main() -> None:
    configure_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--zmq-endpoint", default="tcp://0.0.0.0:5557")
    parser.add_argument("--grpc-address", default="0.0.0.0:50051")
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--hash-seed", default="")
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--engine-type", default="vllm")
    parser.add_argument(
        "--discover-pods-file", default=None,
        help="JSON pod map file; enables per-pod subscribers instead of the "
             "centralized bound endpoint",
    )
    parser.add_argument(
        "--tokenizer-socket", default=None,
        help="UDS tokenizer sidecar socket for the protobuf prompt-scoring "
             "surface; without it prompts are tokenized in-process "
             "(HF registry)",
    )
    args = parser.parse_args()

    # Prompt tokenization for /indexer.v1.IndexerService/GetPodScores:
    # through the sidecar when configured (the reference's UDS path),
    # else in-process via the tokenizer registry.
    if args.tokenizer_socket:
        from llmd_kv_cache_tpu.services.tokenizer.client import UdsTokenizerClient

        uds_client = UdsTokenizerClient(args.tokenizer_socket)

        def tokenize(prompt: str, model_name: str) -> list[int]:
            return uds_client.encode(model_name, prompt).token_ids
    else:
        from llmd_kv_cache_tpu.services.tokenizer.backends import TokenizerRegistry

        registry = TokenizerRegistry()

        def tokenize(prompt: str, model_name: str) -> list[int]:
            return registry.get(model_name).encode(prompt, add_special_tokens=True)

    discover = args.discover_pods_file is not None
    service = IndexerService(
        IndexerConfig(
            token_processor_config=TokenProcessorConfig(
                block_size_tokens=args.block_size, hash_seed=args.hash_seed
            )
        ),
        PoolConfig(
            zmq_endpoint="" if discover else args.zmq_endpoint,
            concurrency=args.concurrency,
            engine_type=args.engine_type,
        ),
        tokenize=tokenize,
    )
    service.start()

    reconciler = None
    if discover:
        reconciler = PodReconciler(
            FileDiscovery(args.discover_pods_file), service.subscriber_manager
        )
        reconciler.start()

    server = serve(args.grpc_address, service)
    try:
        server.wait_for_termination()
    finally:
        if reconciler is not None:
            reconciler.stop()
        service.stop()


if __name__ == "__main__":
    main()
