#!/usr/bin/env python
"""Deployment entry point: indexer service (event plane + scoring RPC).

Counterpart of the reference's ``examples/kv_cache_index_service``. Runs the
sharded event pool with either a centralized bound subscriber or pod
discovery, and serves ``GetPodScores`` over gRPC.

Usage:
  python examples/indexer_service_main.py \
      --zmq-endpoint tcp://0.0.0.0:5557 --grpc-address 0.0.0.0:50051 \
      --block-size 16 --hash-seed 42 [--discover-pods-file pods.json]
"""

import argparse

from llmd_kv_cache_tpu.events.pool import PoolConfig
from llmd_kv_cache_tpu.events.reconciler import FileDiscovery, PodReconciler
from llmd_kv_cache_tpu.scoring import IndexerConfig
from llmd_kv_cache_tpu.services.indexer_service import IndexerService, serve
from llmd_kv_cache_tpu.telemetry import install_signal_dump
from llmd_kv_cache_tpu.utils.logging import configure_from_env


def main() -> None:
    configure_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--zmq-endpoint", default="tcp://0.0.0.0:5557")
    parser.add_argument("--grpc-address", default="0.0.0.0:50051")
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--hash-seed", default="")
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--engine-type", default="vllm")
    parser.add_argument(
        "--scoring-strategy", default="LongestPrefix",
        choices=["LongestPrefix", "HybridAware"],
        help="pod scoring rule; HybridAware values SWA pods by their "
             "usable trailing window (group catalog learned from events)",
    )
    parser.add_argument(
        "--index-backend", default="memory",
        choices=["memory", "redis", "valkey"],
        help="index backend; redis/valkey persist across indexer restarts "
             "and share state between active-active replicas",
    )
    parser.add_argument(
        "--redis-address", default="redis://127.0.0.1:6379",
        help="redis/valkey server for --index-backend redis|valkey",
    )
    parser.add_argument(
        "--discover-pods-file", default=None,
        help="JSON pod map file; enables per-pod subscribers instead of the "
             "centralized bound endpoint",
    )
    parser.add_argument(
        "--discover-k8s-selector", default=None,
        help="pod label selector (e.g. llm-d.ai/inference-serving=true); "
             "enables Kubernetes pod discovery — per-pod subscribers dialed "
             "to tcp://<pod-ip>:<discover-port>",
    )
    parser.add_argument("--discover-namespace", default="",
                        help="namespace for --discover-k8s-selector "
                             "(default: all namespaces)")
    parser.add_argument("--discover-port", type=int, default=5557,
                        help="engine pods' ZMQ event port for k8s discovery")
    parser.add_argument(
        "--metrics-port", type=int, default=0,
        help="serve Prometheus /metrics (+/healthz) on this port; "
             "0 (default) disables the endpoint",
    )
    parser.add_argument(
        "--admin-port", type=int, default=0,
        help="serve the debug surface (/metrics, /healthz, /debug/*) on "
             "this port; 0 (default) disables it",
    )
    parser.add_argument(
        "--admin-host", default="127.0.0.1",
        help="bind address for --metrics-port/--admin-port "
             "(default localhost; 0.0.0.0 exposes beyond the pod)",
    )
    parser.add_argument(
        "--snapshot-dir", default="",
        help="directory for crash-recovery index snapshots + event journal "
             "(docs/resilience.md); empty (default) disables the recovery "
             "subsystem",
    )
    parser.add_argument(
        "--snapshot-interval-s", type=float, default=30.0,
        help="periodic snapshot cadence; 0 = only on shutdown/drain",
    )
    parser.add_argument(
        "--warmup-staleness-bound-s", type=float, default=5.0,
        help="post-restart readiness gate: /healthz stays 503 and scores "
             "are flagged degraded until index staleness drops below this",
    )
    parser.add_argument(
        "--drain-deadline-s", type=float, default=10.0,
        help="total wall-clock budget for the SIGTERM graceful drain",
    )
    parser.add_argument(
        "--span-export", action="store_true",
        help="fleet telemetry: record finished spans into an in-memory "
             "ring served at /debug/spans?since=SEQ on --admin-port, for "
             "the telemetry collector to pull",
    )
    parser.add_argument(
        "--span-export-max-spans", type=int, default=10_000,
        help="span ring depth; beyond it the oldest span is evicted "
             "(counted in kvtpu_trace_dropped_spans_total)",
    )
    parser.add_argument(
        "--pyprof", action="store_true",
        help="continuous profiling: always-on sampling profiler serving "
             "span-attributed folded stacks at /debug/pyprof "
             "(+ /debug/pyprof/capture burst mode) on --admin-port",
    )
    parser.add_argument(
        "--pyprof-hz", type=float, default=67.0,
        help="sampling rate for --pyprof (default 67 Hz)",
    )
    parser.add_argument(
        "--audit", action="store_true",
        help="ground-truth audit plane: record every scored request's "
             "predicted per-pod blocks into a ring served at /debug/audit "
             "on --admin-port, for the collector's score-vs-reality "
             "calibration join",
    )
    parser.add_argument(
        "--audit-max-records", type=int, default=2048,
        help="audit ring depth for --audit (default 2048)",
    )
    parser.add_argument(
        "--workingset", action="store_true",
        help="working-set analytics: sample block reuse on the scoring "
             "path and serve reuse windows at /debug/workingset on "
             "--admin-port for the collector's what-if capacity table",
    )
    parser.add_argument(
        "--workingset-sample-rate", type=float, default=0.05,
        help="spatial sampling rate for --workingset (default 0.05)",
    )
    parser.add_argument(
        "--process-identity", default="",
        help="logical process name stamped on exported spans (what the "
             "collector's critical-path attribution groups by); default: "
             "the shard id, or \"indexer\"",
    )
    parser.add_argument(
        "--tokenizer-socket", default=None,
        help="UDS tokenizer sidecar socket for the protobuf prompt-scoring "
             "surface; without it prompts are tokenized in-process "
             "(HF registry)",
    )
    parser.add_argument(
        "--dump-dir", default=None,
        help="directory for SIGUSR2 flight-recorder dumps (default: "
             "$KVTPU_DUMP_DIR, then the system temp dir); each signal "
             "writes a fresh timestamped JSON file and logs its path",
    )
    args = parser.parse_args()

    # kill -USR2 <pid> dumps the flight-recorder ring to a file under
    # --dump-dir (must be installed from the main thread, hence here and
    # not in the service).
    install_signal_dump(dump_dir=args.dump_dir)

    # Prompt tokenization for /indexer.v1.IndexerService/GetPodScores:
    # through the sidecar when configured (the reference's UDS path),
    # else in-process via the tokenizer registry.
    if args.tokenizer_socket:
        from llmd_kv_cache_tpu.services.tokenizer.client import UdsTokenizerClient

        uds_client = UdsTokenizerClient(args.tokenizer_socket)

        def tokenize(prompt: str, model_name: str) -> list[int]:
            return uds_client.encode(model_name, prompt).token_ids
    else:
        from llmd_kv_cache_tpu.services.tokenizer.backends import TokenizerRegistry

        registry = TokenizerRegistry()

        def tokenize(prompt: str, model_name: str) -> list[int]:
            return registry.get(model_name).encode(prompt, add_special_tokens=True)

    discover = (args.discover_pods_file is not None
                or args.discover_k8s_selector is not None)
    indexer_cfg_dict = {
        "tokenProcessorConfig": {
            "blockSize": args.block_size, "hashSeed": args.hash_seed,
        },
        "kvBlockScorerConfig": {
            "scoringStrategy": "HybridAware"
            if args.scoring_strategy == "HybridAware" else "LongestPrefix",
        },
        "metricsPort": args.metrics_port,
        "adminPort": args.admin_port,
        "adminHost": args.admin_host,
    }
    if args.span_export or args.pyprof or args.workingset or args.audit:
        indexer_cfg_dict["fleetTelemetry"] = {
            "spanExport": args.span_export,
            "maxSpans": args.span_export_max_spans,
            "processIdentity": args.process_identity,
        }
        if args.audit:
            indexer_cfg_dict["fleetTelemetry"]["audit"] = True
            indexer_cfg_dict["fleetTelemetry"]["auditMaxRecords"] = (
                args.audit_max_records)
        if args.pyprof:
            indexer_cfg_dict["fleetTelemetry"]["pyprof"] = {
                "enabled": True, "hz": args.pyprof_hz,
            }
        if args.workingset:
            indexer_cfg_dict["fleetTelemetry"]["workingset"] = {
                "enabled": True,
                "sampleRate": args.workingset_sample_rate,
            }
    if args.snapshot_dir:
        indexer_cfg_dict["recoveryConfig"] = {
            "snapshotDir": args.snapshot_dir,
            "snapshotIntervalS": args.snapshot_interval_s,
            "warmupStalenessBoundS": args.warmup_staleness_bound_s,
            "drainDeadlineS": args.drain_deadline_s,
        }
    if args.index_backend in ("redis", "valkey"):
        key = "valkeyConfig" if args.index_backend == "valkey" else "redisConfig"
        indexer_cfg_dict["kvBlockIndexConfig"] = {
            key: {"address": args.redis_address},
        }
    service = IndexerService(
        IndexerConfig.from_dict(indexer_cfg_dict),
        PoolConfig(
            zmq_endpoint="" if discover else args.zmq_endpoint,
            concurrency=args.concurrency,
            engine_type=args.engine_type,
        ),
        tokenize=tokenize,
    )
    service.start()

    reconciler = None
    if discover:
        if args.discover_k8s_selector is not None:
            from llmd_kv_cache_tpu.events.pool import PodDiscoveryConfig
            from llmd_kv_cache_tpu.events.reconciler import KubernetesDiscovery

            source = KubernetesDiscovery(PodDiscoveryConfig(
                pod_label_selector=args.discover_k8s_selector,
                pod_namespace=args.discover_namespace,
                socket_port=args.discover_port,
            ))
        else:
            source = FileDiscovery(args.discover_pods_file)
        reconciler = PodReconciler(source, service.subscriber_manager)
        reconciler.start()

    server = serve(args.grpc_address, service)
    if service.recovery is not None:
        # SIGTERM → bounded graceful drain (stop intake, flush, final
        # snapshot), then stop the gRPC server so wait_for_termination
        # returns and the normal shutdown path below runs.
        service.install_drain_handler(
            on_complete=lambda: server.stop(grace=1.0))
    try:
        server.wait_for_termination()
    finally:
        if reconciler is not None:
            reconciler.stop()
        service.stop()


if __name__ == "__main__":
    main()
