#!/usr/bin/env python
"""Tensor-parallel serving + continuous batching demo.

A mesh-sharded MiniEngine (Megatron param layout, KV pools sharded on the
kv-heads axis over ``tp``) serves the same tokens as a single-device
engine, while a long prompt admitted with ``enqueue()`` prefills
chunk-at-a-time interleaved with a running decode — the two serving
capabilities the reference's cache layer assumes from its engines
(``file_mapper.py:63-74`` fingerprints tp topology; vLLM provides the
chunked-prefill scheduler), both in-tree here.

Usage:
  PYTHONPATH=. JAX_PLATFORMS=cpu \\
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  python examples/tp_serving_demo.py
"""

import numpy as np

import jax

from llmd_kv_cache_tpu.models.engine import EngineConfig, MiniEngine
from llmd_kv_cache_tpu.models.llama import LlamaConfig, init_params
from llmd_kv_cache_tpu.parallel.mesh import make_mesh

MODEL = "tp-demo"


def engine(cfg, params, mesh=None, **kw):
    return MiniEngine(
        EngineConfig(model=cfg, num_pages=128, max_pages_per_seq=32,
                     model_name=MODEL, pod_identifier="pod-0", **kw),
        params=params, mesh=mesh,
    )


def main() -> None:
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, page_size=4,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, 500, 24).tolist()

    print(f"devices: {len(jax.devices())} × {jax.devices()[0].platform}")

    # 1) TP equivalence: same tokens, sharded or not.
    ref = engine(cfg, params).generate("r", prompt, max_new_tokens=8)
    mesh = make_mesh({"tp": 2}, jax.devices()[:2])
    tp = engine(cfg, params, mesh=mesh).generate("r", prompt, max_new_tokens=8)
    print(f"single-device tokens: {ref}")
    print(f"tp=2 tokens:          {tp}")
    assert tp == ref
    shard = next(iter(
        engine(cfg, params, mesh=mesh).k_cache.addressable_shards))
    print(f"KV pool shard shape (kv-heads axis halved): {shard.data.shape}")

    # 1b) Fused projections under tp: the engine re-layouts the fused
    #     columns per rank (LlamaConfig.fused_interleave) so the wider
    #     matmuls stay Megatron-column-shardable — same tokens again.
    fused_eng = engine(cfg, params, mesh=mesh, fuse_projections=True)
    fused = fused_eng.generate("r", prompt, max_new_tokens=8)
    w = fused_eng.params["layers"][0]["w_qkv"]
    print(f"fused tp=2 tokens:    {fused}  "
          f"(w_qkv {w.shape} sharded {w.sharding.shard_shape(w.shape)})")
    assert fused == ref

    # 2) Continuous batching: a long enqueue()d prompt prefills in chunks
    #    while a short request keeps decoding.
    eng = engine(cfg, params, max_prefill_tokens=8)
    short = eng.add_request("short", rng.integers(1, 500, 8).tolist(),
                            max_new_tokens=12)
    long_req = eng.enqueue("long", rng.integers(1, 500, 80).tolist(),
                           max_new_tokens=2)
    ticks = 0
    while long_req.prefill_pos is not None:
        before = len(short.output)
        eng.step()
        ticks += 1
        print(f"  step {ticks}: long prefilled to {long_req.computed_len} "
              f"tokens, short decoded {len(short.output) - before} more")
    while not (short.done and long_req.done):
        eng.step()
    print(f"short: {len(short.output)} tokens; long: {len(long_req.output)} "
          f"tokens — decode never waited for the 80-token prefill")
    print("=== done")


if __name__ == "__main__":
    main()
