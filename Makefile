# Developer entry points. Tests force the CPU backend (tests/conftest.py);
# `make bench` intentionally runs on whatever accelerator JAX selects (the
# real TPU chip in the benchmark environment).

PY := python
# PYTHONPATH pinned to the repo root: test/dev targets must not inherit
# site customizations that pull in accelerator tunnels (a dead tunnel
# would hang even CPU-backend jax initialization).
CPU_ENV := PYTHONPATH=. JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test unit-test-race tsan asan native bench bench-hotpath bench-hotpath-fleet bench-engine-telemetry bench-shard bench-ragged bench-fp8 bench-disagg bench-fleet bench-pyprof bench-workingset bench-controller bench-graytail bench-fencing bench-incident perf-check verify graft-check verify-examples chaos lint clean

test: native
	$(CPU_ENV) $(PY) -m pytest tests/ -q

# Fault-injection suite (resilience layer): fixed failpoint seed so a
# chaos failure reproduces byte-for-byte on a rerun. KVTPU_LOCKDEP arms
# the runtime lock-order witness (utils/lockdep.py) — chaos schedules are
# exactly where latent A/B lock inversions surface.
chaos: native
	$(CPU_ENV) KVTPU_FAILPOINT_SEED=1337 KVTPU_LOCKDEP=1 \
	  $(PY) -m pytest tests/ -q -m chaos

# Unified lint driver (hack/kvlint.py): resilience (RES-*, swallowed
# errors / non-atomic persistence), observability (OBS-*, span+metric
# namespaces and docs coverage), and concurrency (CONC-*, lock re-entry,
# lock-order cycles, blocking calls and escaping callbacks under locks —
# llmd_kv_cache_tpu/tools/conclint). One `path:line: RULE message`
# format; `--json` for machines.
lint:
	$(PY) hack/kvlint.py llmd_kv_cache_tpu

# Concurrency-focused pass (the reference runs `go test -race` nightly;
# Python has no race detector, so the thread-heavy suites are repeated —
# any single failure fails the target, surfacing flaky races instead of
# hiding them). KVTPU_LOCKDEP=1 swaps every library lock for the lockdep
# witness: the first observed lock-order cycle or illegal re-entry
# raises instead of deadlocking one run in a thousand.
unit-test-race: native tsan
	for i in 1 2 3; do \
	  $(CPU_ENV) KVTPU_LOCKDEP=1 $(PY) -m pytest tests/test_stress.py \
	    tests/test_pool.py tests/test_index.py \
	    tests/test_zmq_integration.py tests/test_evictor.py -q || exit 1; \
	done

# Native race tier: the GIL hides C++ data races from the pytest reruns,
# so the kvio pool and the kvindex engine get hammered under
# ThreadSanitizer directly (go test -race parity for the native side).
tsan:
	$(MAKE) -s -C csrc/kvio tsan
	$(MAKE) -s -C csrc/kvindex tsan

# Native memory tier: ASan+UBSan over the same test binaries — heap
# misuse and UB that TSAN's race instrumentation does not see.
asan:
	$(MAKE) -s -C csrc/kvio asan
	$(MAKE) -s -C csrc/kvindex asan

native:
	$(MAKE) -s -C csrc/kvio
	$(MAKE) -s -C csrc/kvindex

bench: native
	$(PY) bench.py

# Score/ingest hot-path microbenchmark (prefix cache, early-exit lookup,
# batched ingestion) — pure CPU scheduling-path work, so it pins the CPU
# backend unlike `make bench`.
bench-hotpath: native
	$(CPU_ENV) $(PY) hack/bench_hotpath.py

# Fleet-scale data-plane arm (ISSUE 17): batched LookupBlocksBatch
# fan-out vs the per-chunk wire over a 4-shard in-process fleet with
# concurrent zero-copy ingest; hard-asserts the >=5x throughput ratio
# and the ingest-lag staleness bound internally.
bench-hotpath-fleet: native
	$(CPU_ENV) $(PY) hack/bench_hotpath.py --fleet

# Engine-telemetry overhead gate: asserts the per-step hook cost stays
# under 1% of the decode-step p50 (telemetry/engine_telemetry.py).
bench-engine-telemetry: native
	$(CPU_ENV) $(PY) bench.py --engine-telemetry

# Sharded control-plane gate (cluster/): scatter-gather score p99 over a
# 4-shard gRPC fleet at 4x aggregate index size must stay within 1.15x of
# the single-shard baseline (bench_shard_fanout).
bench-shard: native
	$(CPU_ENV) $(PY) bench.py --shards 4

# Ragged single-kernel mixed prefill+decode dispatch vs the padded
# two-kernel path: on CPU an interpret-mode equivalence smoke + padding
# waste comparison; on a real TPU the >=1.5x decode-throughput gate.
bench-ragged: native
	$(CPU_ENV) $(PY) bench.py --ragged

# fp8 vs bf16 decode KV-bandwidth probe (VERDICT r5 item 1); analytic
# bytes/step + interpret smoke on CPU, measured ms/step on a real chip.
bench-fp8: native
	$(CPU_ENV) $(PY) bench.py --fp8-bandwidth

# Prefill/decode disaggregation gate (offload/handoff): decode-heavy
# replay where a prefill pod + decode pod pair hands KV off over the
# transfer tier vs a monolithic baseline; on CPU a correctness + trace-
# continuity smoke, on a real chip the out_tok/s-at-fixed-TTFT gate.
bench-disagg: native
	$(CPU_ENV) $(PY) bench.py --disagg

# Fleet-telemetry overhead gate (telemetry/ + services/telemetry_
# collector): per-span export cost (identity stamp + seq + ring append)
# must stay under 1% of the Python-path score p50; also reports
# /debug/spans pull and trace-assembly round timings.
bench-fleet: native
	$(CPU_ENV) $(PY) bench.py --fleet-telemetry

# Continuous-profiling overhead gate (telemetry/sampling_profiler): the
# always-on sampler's pass-cost x hz CPU fraction must stay under 1% of
# the score p50; also emits the hot-function shares the perf sentinel
# diffs.
bench-pyprof: native
	$(CPU_ENV) $(PY) bench.py --pyprof-overhead

# Working-set analytics gates (telemetry/workingset): the SHARDS-sampled
# miss-ratio curve must track an exact LRU-simulation oracle within a
# bounded error, and the per-score hook cost must stay under 1% of the
# score p50.
bench-workingset: native
	$(CPU_ENV) $(PY) bench.py --workingset

# Fleet-controller chaos arm (control/): traffic-flip re-role, 4x index
# ramp shard scale-up, and flap injection against a modeled fleet; the
# flap-injection executed-action count is the perf-sentinel value
# (hysteresis must bound it).
bench-controller: native
	$(CPU_ENV) $(PY) bench.py --controller

# Gray-failure tail-tolerance gate (resilience/cluster, PR 16): one of
# four shards delayed 10x via a seeded delay failpoint — hedged fan-out
# must hold the score p99 within 2x of the interleaved healthy baseline
# (and under half the injected delay), breakers must stay closed, every
# deadline overrun must be shed or flagged degraded, and the healthy-path
# hedging bookkeeping must cost < 1% of the score p50 (the perf-sentinel
# value).
bench-graytail: native
	$(CPU_ENV) $(PY) bench.py --graytail

# Ground-truth audit plane gate (telemetry/audit.py): the per-score
# prediction hook must cost < 1% of the Python-path score p50 (the
# perf-sentinel value); the once-per-request outcome append is reported
# informationally.
bench-audit: native
	$(CPU_ENV) $(PY) bench.py --audit

# Epoch-fencing gate (cluster/membership.py): the per-score fence check
# (MembershipTable.check_request) must cost < 1% of the score p50 — the
# fencing plane rides the hot path on every request, so its clean path
# has to be a lock-free cached-decision compare.
bench-fencing: native
	$(CPU_ENV) $(PY) bench.py --fencing

# Incident black-box gate (telemetry/incident.py): the alert-edge
# trigger hook (IncidentManager.maybe_open) must cost < 1% of the score
# p50 — the evidence fan-out and the bundle write run on a detached
# worker, and the bench proves the accepted edge never pays them.
bench-incident: native
	$(CPU_ENV) $(PY) bench.py --incident

# Perf-regression sentinel: run the profiling + working-set gates and the
# controller chaos arm, then diff their values and hot-function shares
# against the committed baseline manifest. Emits machine-verdict
# `PERF PASS|FAIL ...` lines; fails on regression.
perf-check: native
	$(CPU_ENV) $(PY) bench.py --pyprof-overhead > /tmp/kvtpu_pyprof_bench.json
	$(CPU_ENV) $(PY) bench.py --workingset > /tmp/kvtpu_workingset_bench.json
	$(CPU_ENV) $(PY) bench.py --controller > /tmp/kvtpu_controller_bench.json
	$(CPU_ENV) $(PY) bench.py --graytail > /tmp/kvtpu_graytail_bench.json
	$(CPU_ENV) $(PY) bench.py --audit > /tmp/kvtpu_audit_bench.json
	$(CPU_ENV) $(PY) bench.py --fencing > /tmp/kvtpu_fencing_bench.json
	$(CPU_ENV) $(PY) bench.py --incident > /tmp/kvtpu_incident_bench.json
	$(CPU_ENV) $(PY) hack/bench_hotpath.py --fleet > /tmp/kvtpu_fleet_bench.json
	$(PY) hack/perf_sentinel.py --baseline benchmarking/perf_baseline.json \
	  --results pyprof-overhead=/tmp/kvtpu_pyprof_bench.json \
	  --results workingset=/tmp/kvtpu_workingset_bench.json \
	  --results controller=/tmp/kvtpu_controller_bench.json \
	  --results graytail=/tmp/kvtpu_graytail_bench.json \
	  --results audit=/tmp/kvtpu_audit_bench.json \
	  --results fencing=/tmp/kvtpu_fencing_bench.json \
	  --results incident=/tmp/kvtpu_incident_bench.json \
	  --results hotpath-fleet=/tmp/kvtpu_fleet_bench.json

# The pre-merge bundle: conventions lint + the perf sentinel.
verify: lint perf-check

# Run every runnable example headlessly (the reference's
# hack/verify-examples.sh equivalent).
verify-examples: native
	$(CPU_ENV) $(PY) examples/offline_events.py
	$(CPU_ENV) $(PY) examples/fleet_demo.py
	$(CPU_ENV) $(PY) examples/tp_serving_demo.py
	$(CPU_ENV) $(PY) examples/long_context_sp.py
	$(CPU_ENV) $(PY) examples/serve_hf_checkpoint.py
	$(CPU_ENV) $(PY) examples/redis_indexer.py
	$(CPU_ENV) $(PY) examples/fp8_kv_serving.py
	$(CPU_ENV) $(PY) examples/sharded_cluster_demo.py

# Developer check on the CPU backend (the driver separately compile-checks
# entry() on the real chip).
graft-check:
	$(CPU_ENV) $(PY) -c "import __graft_entry__, jax; fn, a = __graft_entry__.entry(); \
	  print(jax.jit(fn)(*a).shape)"
	$(CPU_ENV) $(PY) -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

clean:
	$(MAKE) -C csrc/kvio clean
	$(MAKE) -C csrc/kvindex clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
